"""InferenceModel — the TPU-native inference runtime, parity with the
reference's multi-backend ``InferenceModel``
(``pipeline/inference/InferenceModel.scala:30-67,622-656``):

* ``concurrent_num``-deep **replica queue**: the reference clones the model
  ``concurrentNum`` times into a ``LinkedBlockingQueue`` so concurrent callers
  each hold one replica (``InferenceModel.scala:67``). Here params are
  immutable jax arrays and the compiled predict fn is pure, so replicas share
  weights; the queue holds permits that bound in-flight predictions and make
  ``predict`` safely callable from many threads (serving threads, ``L9``).
* **multi-format load**: the reference loads BigDL/Caffe/TF/Torch/OpenVINO
  (``InferenceModel.scala:80-450``); the TPU-native formats are the ZooModel
  one-file ``.npz`` (``load(path)``), a training checkpoint directory
  (``load_checkpoint``), or an in-memory ``KerasNet`` (``from_keras``).
* **precision paths**: fp32, bf16 (MXU native), and **int8 weight-only
  quantization** with per-channel scales — the AQT-style replacement for the
  reference's OpenVINO int8 calibration path
  (``InferenceModel.scala:350-450``, ``OpenVinoInferenceSupportive.scala``);
  int8 weights stay int8 in HBM (4x smaller, bandwidth-bound layers speed
  up) and are dequantized inside the fused XLA program.
* **batch bucketing**: inputs are padded to the next power-of-two batch so
  arbitrary request sizes reuse a small set of compiled programs instead of
  recompiling per shape (XLA static-shape discipline).
"""

from __future__ import annotations

import queue
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...common.reliability import RetryPolicy
from ...models.common.zoo_model import load_model
from ...observability import default_registry, instrument_jit
from ...parallel import mesh as mesh_lib
from ..api.keras.engine import KerasNet, intercept_layer_calls
from ...utils.checkpoint import CheckpointManager

__all__ = ["InferenceModel"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


#: chunked predicts keep at most this many chunk OUTPUTS resident in HBM:
#: chunk i-1 is read back while chunk i runs / i+1 dispatches (ADVICE r5 —
#: dispatching every chunk before any readback held the whole output set
#: on device until collect()). 2 preserves the dispatch/readback overlap;
#: the common serving case (one chunk) is untouched.
_MAX_INFLIGHT_CHUNKS = 2


# ---------------------------------------------------------------------------
# int8 weight-only quantization (AQT-style)
# ---------------------------------------------------------------------------

_QUANT_MIN_SIZE = 512  # leaves smaller than this stay float (biases, scalars)


def quantize_int8(params) -> Tuple[Any, Any]:
    """Split a float param tree into (int8-or-float tree, scale-or-None tree).

    Per-channel symmetric quantization over the last axis: for a Dense kernel
    ``(in, out)`` each output column gets its own scale — the same granularity
    OpenVINO's calibration uses for FC layers. Small leaves (biases, norms)
    are kept in float; their footprint is negligible and quantizing them
    costs accuracy for nothing."""

    def q(leaf):
        a = np.asarray(jax.device_get(leaf))
        if a.dtype.kind != "f" or a.size < _QUANT_MIN_SIZE or a.ndim < 1:
            return a, None
        axes = tuple(range(a.ndim - 1)) if a.ndim > 1 else (0,)
        amax = np.max(np.abs(a), axis=axes, keepdims=True)
        scale = (amax / 127.0).astype(np.float32)
        scale = np.where(scale == 0, 1.0, scale)
        qa = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
        return qa, np.squeeze(scale, axis=axes) if a.ndim > 1 else scale

    flat, treedef = jax.tree_util.tree_flatten(params)
    qs, scales = zip(*(q(l) for l in flat)) if flat else ((), ())
    return (jax.tree_util.tree_unflatten(treedef, list(qs)),
            jax.tree_util.tree_unflatten(treedef, list(scales)))


def _quantize_layer_entry(sub, act_scale: float):
    """Per-layer static-int8 params: int8 weight + per-out-channel scale +
    the calibrated activation scale (what ``quantized_call`` consumes)."""
    W = np.asarray(jax.device_get(sub["W"]))
    axes = tuple(range(W.ndim - 1))
    amax = np.max(np.abs(W), axis=axes)
    w_scale = np.where(amax == 0, 1.0, amax / 127.0).astype(np.float32)
    entry = {"W": np.clip(np.round(W / w_scale), -127, 127).astype(np.int8),
             "w_scale": w_scale, "x_scale": np.float32(act_scale)}
    for k, v in sub.items():
        if k != "W":
            entry[k] = np.asarray(jax.device_get(v))
    return entry


def dequantize_int8(q_tree, scale_tree, dtype=jnp.float32):
    """Inverse of :func:`quantize_int8`, run INSIDE the jitted predict so the
    int8 leaves are what lives in HBM."""

    def dq(q, s):
        if s is None:
            return q.astype(dtype) if q.dtype.kind == "f" else q
        return q.astype(dtype) * jnp.asarray(s, dtype)

    return jax.tree.map(dq, q_tree, scale_tree,
                        is_leaf=lambda x: x is None or not isinstance(
                            x, (dict, list, tuple)))


# ---------------------------------------------------------------------------
# InferenceModel
# ---------------------------------------------------------------------------

class InferenceModel:
    """Replica-queue batched inference runtime.

    >>> im = InferenceModel(concurrent_num=4)
    >>> im.load("/path/model.npz", dtype="bfloat16")
    >>> probs = im.predict(x)                       # thread-safe
    """

    def __init__(self, concurrent_num: int = 1, *,
                 max_batch_size: int = 4096, registry=None,
                 readback_retry: Optional[RetryPolicy] = None):
        if concurrent_num < 1:
            raise ValueError("concurrent_num must be >= 1")
        self.concurrent_num = int(concurrent_num)
        self.max_batch_size = int(max_batch_size)
        #: chunk readbacks cross the device link (a tunneled/remote
        #: transport on some deployments) — transient transport errors
        #: retry under this policy instead of failing the whole predict;
        #: non-transport errors (shape bugs, OOM) propagate immediately
        self._readback_retry = readback_retry if readback_retry \
            is not None else RetryPolicy(
                max_attempts=3, base_delay=0.05, max_delay=0.5,
                retryable=(ConnectionError, OSError))
        self.metrics = registry if registry is not None else default_registry()
        self._m_permit_wait = self.metrics.histogram(
            "zoo_inference_permit_wait_seconds",
            "wait for a replica permit per predict dispatch")
        self._m_batch_time = self.metrics.histogram(
            "zoo_inference_batch_seconds",
            "predict dispatch to readback completion per batch "
            "(device time + transfer; overlapped callers hide it)")
        self._m_batches = self.metrics.counter(
            "zoo_inference_batches_total", "predict batches collected")
        self._m_records = self.metrics.counter(
            "zoo_inference_records_total", "records predicted")
        self.mesh = mesh_lib.global_mesh()
        # replica-permit pool: exactly concurrent_num tokens ever exist,
        # so the explicit bound documents the invariant and every return
        # is put_nowait — a put into this pool can never block (ZL011)
        self._permits: "queue.Queue[int]" = queue.Queue(
            maxsize=self.concurrent_num)
        for i in range(self.concurrent_num):
            self._permits.put_nowait(i)
        self._model: Optional[KerasNet] = None
        self._params = None
        self._net_state = None
        self._scales = None          # int8 path only
        self._dtype = jnp.float32
        self._predict = None         # shape-polymorphic jitted fn

    # ---- loaders (InferenceModel.scala:80-450 family) ---------------------
    def load(self, path: str, *, dtype: str = "float32",
             quantize: Optional[str] = None,
             calibrate=None) -> "InferenceModel":
        """Load a ZooModel one-file ``.npz`` (``doLoadBigDL`` role)."""
        return self.from_keras(load_model(path), dtype=dtype,
                               quantize=quantize, calibrate=calibrate)

    def load_checkpoint(self, model: KerasNet, ckpt_dir: str, *,
                        dtype: str = "float32",
                        quantize: Optional[str] = None,
                        calibrate=None) -> "InferenceModel":
        """Load the newest training snapshot from ``ckpt_dir`` into
        ``model``'s architecture (``doLoadTF(checkpoint)`` role)."""
        if model.params is None:
            model.init_weights()
        mgr = CheckpointManager(ckpt_dir)
        # verified restore with fallback (docs/guides/TRAINING.md): a
        # torn newest snapshot is skipped and the next valid one loads —
        # serving never boots on bad weights. READ-ONLY (quarantine=False):
        # this process does not own the directory, and what looks
        # uncommitted may be a live training run's save in flight
        out = mgr.restore_latest({"params": model.params,
                                  "net_state": model.net_state},
                                 quarantine=False)
        if out is None:
            raise FileNotFoundError(f"no valid snapshot in {ckpt_dir}")
        _step, trees, _meta = out
        model.params = trees["params"]
        model.net_state = trees["net_state"]
        return self.from_keras(model, dtype=dtype, quantize=quantize,
                               calibrate=calibrate)

    def from_keras(self, model: KerasNet, *, dtype: str = "float32",
                   quantize: Optional[str] = None,
                   calibrate=None) -> "InferenceModel":
        """Wrap an in-memory KerasNet/ZooModel (weights already present).

        ``quantize="int8"`` alone is weight-only (int8 in HBM, float
        compute). Adding ``calibrate=representative_batch`` runs one eager
        calibration pass recording each Dense/Conv2D input range, then
        executes those layers as int8 x int8 -> int32 MXU ops with a fused
        rescale — the native equivalent of the reference's OpenVINO
        calibrate-then-int8 pipeline (``InferenceModel.scala:80-450``,
        ``OpenVinoInferenceSupportive.scala:61-68``)."""
        if model.params is None:
            model.init_weights()
        self._model = model
        self._dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                       "bf16": jnp.bfloat16}[dtype]
        params, net_state = model.params, model.net_state
        self._act_scales = None
        if calibrate is not None and quantize != "int8":
            raise ValueError(
                "calibrate= requires quantize='int8' (a calibration batch "
                "without a quantized mode would be silently ignored)")
        if quantize is None:
            cast = (lambda a: a.astype(self._dtype)
                    if hasattr(a, "dtype") and a.dtype == jnp.float32
                    and self._dtype != jnp.float32 else a)
            self._params = jax.tree.map(cast, params)
            self._scales = None
        elif quantize == "int8":
            repl = mesh_lib.replicated_sharding(self.mesh)
            if calibrate is not None:
                self._act_scales = self._calibrate(model, params, net_state,
                                                   calibrate)
                q = self._rewrite_quantized(params, self._act_scales)
                self._params = jax.device_put(q, repl)
                self._scales = None
            else:
                q, s = quantize_int8(params)
                # quantize_int8 produces HOST numpy arrays; pin them on
                # device once — otherwise every predict re-uploads the whole
                # int8 weight set (catastrophic over a tunneled device
                # link). Replicated over the mesh, matching the batch-
                # sharded inputs.
                self._params = jax.device_put(q, repl)
                self._scales = jax.device_put(s, repl)
        else:
            raise ValueError(f"unknown quantize mode {quantize!r}; "
                             "use None or 'int8'")
        self._net_state = net_state
        model, dtype, scales = self._model, self._dtype, self._scales
        act_scales = self._act_scales

        def qhook(layer, p, s, x, training, rng):
            if (act_scales is not None and layer.name in act_scales
                    and isinstance(p, dict) and "x_scale" in p
                    and not isinstance(x, (list, tuple))):
                return layer.quantized_call(p, x), (s or {})
            return None

        def run(params, net_state, x):
            if scales is not None:
                params = dequantize_int8(params, scales, dtype)
            if dtype != jnp.float32:
                x = jax.tree.map(
                    lambda a: a.astype(dtype) if a.dtype.kind == "f" else a, x)
            with intercept_layer_calls(qhook if act_scales else None):
                yp, _ = model.apply(params, net_state, x, training=False,
                                    rng=None)
            return jax.tree.map(lambda a: a.astype(jnp.float32)
                                if a.dtype == jnp.bfloat16 else a, yp)

        # one shape-polymorphic jitted fn; jax.jit caches one executable per
        # padded batch size (bounded by the power-of-two bucketing below) and
        # is itself thread-safe. `params` is rebound only to its dequantized
        # view — self._params must survive every call, so donation is wrong.
        # instrument_jit: each new padded batch size is an expected compile
        # (bucketing bounds them); a retrace storm here means a caller is
        # bypassing the bucketing
        self._predict = instrument_jit(  # zoolint: disable=ZL008
            run, name="inference.predict", registry=self.metrics)
        return self

    @staticmethod
    def _quantizable(layer) -> bool:
        """True when the class that provides the layer's EFFECTIVE ``call``
        also provides a matching ``quantized_call`` — a subclass that
        overrides ``call`` (ShareConvolution2D's explicit padding,
        Deconvolution2D's transpose) must not inherit a quantized path with
        different semantics."""
        for cls in type(layer).__mro__:
            if "call" in cls.__dict__:
                return "quantized_call" in cls.__dict__
        return False

    @staticmethod
    def _calibrate(model, params, net_state, calibrate
                   ) -> Dict[str, Tuple[float, tuple]]:
        """One eager forward over the calibration batch, recording per
        quantizable layer the activation scale AND the kernel shape —
        ``{name: (x_scale, W_shape)}`` — so the rewrite can refuse
        name-colliding layers in other containers. The max of colliding
        ranges is taken (conservative)."""
        records: Dict[str, float] = {}

        shapes: Dict[str, tuple] = {}

        def rec(layer, p, s, x, training, rng):
            if (InferenceModel._quantizable(layer) and isinstance(p, dict)
                    and "W" in p and not isinstance(x, (list, tuple))):
                amax = float(jnp.abs(x).max())
                records[layer.name] = max(records.get(layer.name, 0.0), amax)
                shapes[layer.name] = tuple(p["W"].shape)
            return None

        xs = [jnp.asarray(a) for a in _as_list(calibrate)]
        with intercept_layer_calls(rec):
            model.apply(params, net_state, xs if len(xs) > 1 else xs[0],
                        training=False, rng=None)
        if not records:
            raise ValueError("calibration found no quantizable layer "
                             "(Dense/Convolution2D) in the model")
        return {name: (max(amax, 1e-8) / 127.0, shapes[name])
                for name, amax in records.items()}

    @staticmethod
    def _rewrite_quantized(params, act_scales):
        """Replace each calibrated layer's param subtree with its static-int8
        entry, recursing through nested containers. A subtree is rewritten
        only when BOTH the layer name and the kernel shape recorded at
        calibration match — a non-quantizable layer in another container
        that merely shares a calibrated layer's name keeps its float params
        (the collision _calibrate's docstring warns about)."""
        def rewrite(tree):
            if not isinstance(tree, dict):
                return tree
            out = {}
            for k, v in tree.items():
                entry = act_scales.get(k)
                if (entry is not None and isinstance(v, dict) and "W" in v
                        and tuple(v["W"].shape) == entry[1]):
                    out[k] = _quantize_layer_entry(v, entry[0])
                else:
                    out[k] = rewrite(v)
            return out
        return rewrite(params)

    # ---- predict (InferenceModel.scala:622-656) ---------------------------
    def predict(self, x, batch_size: Optional[int] = None):
        """Batched predict. Blocks while all ``concurrent_num`` replicas are
        busy (the reference blocks on the replica queue,
        ``InferenceModel.scala:622-656``). Thread-safe."""
        return self.predict_async(x, batch_size)()

    def predict_async(self, x, batch_size: Optional[int] = None,
                      block: bool = True):
        """Dispatch a predict WITHOUT blocking on readback. Returns a
        zero-arg ``collect`` callable: the device work is enqueued here
        (XLA dispatch is asynchronous), ``collect()`` blocks on the
        transfer and returns the numpy result. The replica permit is held
        until ``collect`` runs — call it exactly once. Inputs larger than
        ``max_batch_size`` dispatch in chunks with at most
        ``_MAX_INFLIGHT_CHUNKS`` chunk outputs resident in HBM (older
        chunks are read back while newer ones dispatch).

        With ``block=False`` the call returns None instead of waiting when
        every replica permit is in flight. A single-threaded pipeline MUST
        use this mode for its second in-flight dispatch: with
        ``concurrent_num=1`` a blocking dispatch-before-collect would
        deadlock on the permit its own later collect() releases. The serve
        loop (``serving/server.py``) overlaps batches this way."""
        if self._model is None:
            raise RuntimeError("no model loaded; call load()/from_keras() first")
        xs = [np.asarray(a) for a in _as_list(x)]
        n = xs[0].shape[0]
        if n == 0:
            raise ValueError("predict called with an empty batch")
        dp = mesh_lib.data_parallel_size(self.mesh)
        # the chunk cap is a power of two <= max_batch_size so padded chunks
        # never exceed the user's HBM bound
        cap = max(_next_pow2(self.max_batch_size + 1) // 2, dp)
        cap = min(cap, max(_next_pow2(n), dp))
        if block:
            t_wait = time.perf_counter()
            permit = self._permits.get()
            self._m_permit_wait.observe(time.perf_counter() - t_wait)
        else:
            try:
                permit = self._permits.get_nowait()
            except queue.Empty:
                return None
            self._m_permit_wait.observe(0.0)
        t_dispatch = time.perf_counter()
        deferred = []
        outs = []       # host results, in chunk order

        def readback_oldest():
            # device_get rides the device transport: retried under the
            # readback policy so one dropped link round-trip does not
            # fail a predict whose compute already succeeded
            yp, m = deferred.pop(0)
            host = self._readback_retry.call(
                lambda: jax.tree.map(lambda a: np.asarray(
                    jax.device_get(a)), yp),
                op="inference.readback", registry=self.metrics)
            outs.append(jax.tree.map(lambda a, mm=m: a[:mm], host))

        try:
            for i in range(0, n, cap):
                if len(deferred) >= _MAX_INFLIGHT_CHUNKS:
                    # bound the in-flight chunk outputs: read back the
                    # oldest before dispatching another, so a many-chunk
                    # predict never holds every chunk output in HBM
                    readback_oldest()
                chunk = [a[i:i + cap] for a in xs]
                m = chunk[0].shape[0]
                padded = max(_next_pow2(m), dp)
                if m != padded:
                    chunk = [np.concatenate(
                        [a, np.repeat(a[-1:], padded - m, axis=0)], axis=0)
                        for a in chunk]
                sharding = mesh_lib.batch_sharding(self.mesh)
                # each chunk IS the batched transfer (bounded by
                # max_batch_size so padded chunks fit the HBM budget)
                chunk_d = [jax.device_put(jnp.asarray(a), sharding)  # zoolint: disable=ZL009
                           for a in chunk]
                yp = self._predict(self._params, self._net_state,
                                   chunk_d if len(chunk_d) > 1 else chunk_d[0])
                deferred.append((yp, m))
        except BaseException:
            self._permits.put_nowait(permit)
            raise

        done = [False]

        def collect():
            if done[0]:
                raise RuntimeError("predict_async result already collected")
            done[0] = True
            try:
                while deferred:
                    readback_oldest()
                self._m_batch_time.observe(time.perf_counter() - t_dispatch)
                self._m_batches.inc()
                self._m_records.inc(n)
                return jax.tree.map(
                    lambda *ys: np.concatenate(ys, axis=0), *outs)
            finally:
                self._permits.put_nowait(permit)

        return collect

    def predict_classes(self, x, zero_based: bool = True):
        from ...utils.prediction import probs_to_classes
        return probs_to_classes(self.predict(x), zero_based=zero_based)

    # ---- introspection ----------------------------------------------------
    def memory_bytes(self) -> int:
        """Weight footprint in HBM — shows the int8 4x reduction. Reads only
        dtype/shape metadata (no device transfer)."""
        return sum(int(np.prod(np.shape(leaf))) * np.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree_util.tree_leaves(self._params))
