"""Inference runtime (``pipeline/inference`` of the reference, L8)."""

from .inference_model import InferenceModel

__all__ = ["InferenceModel"]
