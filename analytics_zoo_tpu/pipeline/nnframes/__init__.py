"""NNFrames — columnar-table ML pipeline (``pipeline/nnframes`` of the
reference, L6)."""

from .nn_estimator import NNClassifier, NNClassifierModel, NNEstimator, NNModel
from .nn_image_reader import NNImageReader

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader"]
