"""NNFrames — the DataFrame-pipeline adapter, parity with the reference's
``pipeline/nnframes/NNEstimator.scala`` / ``NNClassifier.scala``.

The reference plugs BigDL training into Spark ML Pipelines:
``NNEstimator.fit(df)`` converts DataFrame rows to Samples via
``Preprocessing`` chains (``NNEstimator.scala:385-412``), trains through
``InternalDistriOptimizer`` (``:414-479``), and returns an ``NNModel``
transformer that appends a prediction column (``Predictor.scala:136-208``).

TPU-native re-design: the "DataFrame" is a **columnar table** — a plain dict
of column-name → numpy array (arrow-style), the natural host-side format for
feeding device-resident batches. The estimator/transformer contract
(`fit(table) -> NNModel`, `NNModel.transform(table) -> table + prediction`)
and the param surface (feature/label cols, batch size, max epoch, caching)
are kept.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ...common.triggers import Trigger
from ...feature.feature_set import FeatureSet
from ..api.keras.engine import KerasNet
from ..estimator.estimator import Estimator

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel"]

Table = Dict[str, np.ndarray]


def _assemble(table: Table, cols: Sequence[str]) -> np.ndarray:
    """VectorAssembler role: concatenate columns into one float feature
    matrix. Scalar columns become width-1; array columns keep their width
    (``NNEstimator.scala:385-403`` unwraps ML vectors the same way)."""
    parts = []
    for c in cols:
        if c not in table:
            raise KeyError(f"column {c!r} not in table; have {sorted(table)}")
        a = np.asarray(table[c])
        parts.append(a[:, None] if a.ndim == 1 else a.reshape(a.shape[0], -1))
    return np.concatenate(parts, axis=1).astype(np.float32)


class NNEstimator:
    """``NNEstimator(model, criterion, samplePreprocessing)``
    (``NNEstimator.scala:160-209``). ``feature_preprocessing`` maps the
    table to the model's input array(s) — pass a callable for multi-input
    models (e.g. ``ColumnFeatureInfo.input_arrays``); by default the
    ``features_col`` columns are assembled into one float matrix."""

    def __init__(self, model: KerasNet, criterion: Any = "mse",
                 feature_preprocessing: Optional[Callable[[Table], Any]] = None):
        self.model = model
        self.criterion = criterion
        self.feature_preprocessing = feature_preprocessing
        self.features_col: List[str] = ["features"]
        self.label_col = "label"
        self.prediction_col = "prediction"
        self.batch_size = 32
        self.max_epoch = 1
        self.optim_method: Any = "adam"
        self.end_trigger: Optional[Trigger] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.model_dir: Optional[str] = None
        self.label_dtype = np.float32

    # ---- Spark-ML-style param setters (NNEstimator.scala param surface) ---
    def set_features_col(self, *cols: str) -> "NNEstimator":
        self.features_col = list(cols)
        return self

    def set_label_col(self, col: str) -> "NNEstimator":
        self.label_col = col
        return self

    def set_prediction_col(self, col: str) -> "NNEstimator":
        self.prediction_col = col
        return self

    def set_batch_size(self, bs: int) -> "NNEstimator":
        self.batch_size = int(bs)
        return self

    def set_max_epoch(self, n: int) -> "NNEstimator":
        self.max_epoch = int(n)
        return self

    def set_optim_method(self, opt: Any) -> "NNEstimator":
        self.optim_method = opt
        return self

    def set_end_when(self, trigger: Trigger) -> "NNEstimator":
        self.end_trigger = trigger
        return self

    def set_checkpoint(self, path: str,
                       trigger: Optional[Trigger] = None) -> "NNEstimator":
        """``setCheckpoint`` (``NNEstimator.scala:131-140``)."""
        self.model_dir = path
        self.checkpoint_trigger = trigger
        return self

    # ---- fit (NNEstimator.scala:414-479) ----------------------------------
    def _features(self, table: Table):
        if self.feature_preprocessing is not None:
            return self.feature_preprocessing(table)
        return _assemble(table, self.features_col)

    def _label(self, table: Table) -> np.ndarray:
        if self.label_col not in table:
            raise KeyError(f"label column {self.label_col!r} not in table")
        y = np.asarray(table[self.label_col])
        y = y.astype(self.label_dtype)
        return y[:, None] if y.ndim == 1 and self.label_dtype == np.float32 else y

    def fit(self, table: Table, validation_table: Optional[Table] = None,
            ) -> "NNModel":
        x = self._features(table)
        y = self._label(table)
        fs = FeatureSet.array(x, y)
        est = Estimator(self.model, optim_methods=self.optim_method,
                        model_dir=self.model_dir)
        val = None
        if validation_table is not None:
            val = FeatureSet.array(self._features(validation_table),
                                   self._label(validation_table))
        est.train(fs, self.criterion, batch_size=self.batch_size,
                  nb_epoch=self.max_epoch, end_trigger=self.end_trigger,
                  checkpoint_trigger=self.checkpoint_trigger,
                  validation_set=val)
        return self._wrap_model()

    def _wrap_model(self) -> "NNModel":
        return NNModel(self.model,
                       feature_preprocessing=self.feature_preprocessing,
                       features_col=self.features_col,
                       prediction_col=self.prediction_col,
                       batch_size=self.batch_size)


class _ZooPickler:
    """Pickle helpers that serialize REGISTRY objects by name: layers store
    resolved activation callables (``jax.nn.relu`` is a ``custom_jvp``
    object, the ``hard_sigmoid``/``linear`` entries are lambdas — none
    pickle), so identity-match them back to their ``ACTIVATIONS`` key and
    re-resolve on load."""

    @staticmethod
    def dumps(obj) -> bytes:
        import io
        import pickle

        from ..api.keras.layers.core import ACTIVATIONS

        class P(pickle.Pickler):
            def persistent_id(self, o):
                for name, fn in ACTIVATIONS.items():
                    if o is fn:
                        return ("zoo_activation", name)
                return None

        buf = io.BytesIO()
        P(buf).dump(obj)
        return buf.getvalue()

    @staticmethod
    def load(f):
        import pickle

        class U(pickle.Unpickler):
            def persistent_load(self, pid):
                kind, name = pid
                if kind == "zoo_activation":
                    from ..api.keras.layers.core import ACTIVATIONS
                    return ACTIVATIONS[name]
                raise pickle.UnpicklingError(f"unknown persistent id {pid}")

        return U(f).load()


class NNModel:
    """Transformer: appends ``prediction_col`` to the table
    (``NNModel.transform`` → ``Predictor.scala:136-208``)."""

    def __init__(self, model: KerasNet, *,
                 feature_preprocessing: Optional[Callable] = None,
                 features_col: Sequence[str] = ("features",),
                 prediction_col: str = "prediction",
                 batch_size: int = 32):
        self.model = model
        self.feature_preprocessing = feature_preprocessing
        self.features_col = list(features_col)
        self.prediction_col = prediction_col
        self.batch_size = batch_size

    def _features(self, table: Table):
        if self.feature_preprocessing is not None:
            return self.feature_preprocessing(table)
        return _assemble(table, self.features_col)

    def transform(self, table: Table) -> Table:
        preds = self.model.predict(self._features(table),
                                   batch_size=self.batch_size)
        out = dict(table)
        out[self.prediction_col] = self._postprocess(np.asarray(preds))
        return out

    def _postprocess(self, preds: np.ndarray) -> np.ndarray:
        return preds

    # ---- persistence (NNEstimator.scala:60-72 read/write region,
    # DefaultParamsWriterWrapper.scala) ------------------------------------
    def save(self, path: str, over_write: bool = True) -> str:
        """Persist the FITTED transformer — weights, architecture,
        preprocessing chain, and column config — as one file, the role of
        the reference's ML-pipeline ``NNModel.write`` (params +
        serialized module + sample preprocessing). A fresh process
        ``NNModel.load(path).transform(table)``s without re-fitting.

        The preprocessing callable must be picklable (a ``Preprocessing``
        instance, named function, or functools.partial — the same
        serializable-stages contract Spark ML imposes); lambdas raise with
        that guidance."""
        import copy
        import os
        import pickle

        import jax

        if os.path.exists(path) and not over_write:
            raise FileExistsError(f"{path} exists and over_write=False")
        clean = copy.copy(self)
        model = copy.copy(self.model)
        # jitted/closure state does not persist: the training loop caches
        # compiled programs, the compile spec holds optax closures, and the
        # optimizer state is checkpoint territory (the reference's saved
        # NNModel likewise carries weights, not optimizer state)
        for attr in ("_loop", "_compiled", "opt_state"):
            if hasattr(model, attr):
                setattr(model, attr, None)

        def host(a):
            return np.asarray(jax.device_get(a))

        if model.params is not None:
            model.params = jax.tree.map(host, model.params)
        if getattr(model, "net_state", None):
            model.net_state = jax.tree.map(host, model.net_state)
        clean.model = model
        try:
            blob = _ZooPickler.dumps(clean)
        except (pickle.PicklingError, AttributeError, TypeError) as e:
            raise ValueError(
                f"NNModel.save: the transformer is not picklable ({e}) — "
                f"feature_preprocessing must be a Preprocessing instance, "
                f"a module-level function, or a functools.partial, not a "
                f"lambda/closure") from e
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return path

    @staticmethod
    def load(path: str) -> "NNModel":
        """``NNModel.read.load`` — restores the fitted transformer (the
        concrete subclass, e.g. ``NNClassifierModel``, round-trips via the
        pickle class tag)."""
        with open(path, "rb") as f:
            obj = _ZooPickler.load(f)
        if not isinstance(obj, NNModel):
            raise ValueError(f"{path} does not contain an NNModel "
                             f"(got {type(obj).__name__})")
        return obj


class NNClassifier(NNEstimator):
    """``NNClassifier`` (``NNClassifier.scala``): integer labels, argmax
    predictions."""

    def __init__(self, model: KerasNet,
                 criterion: Any = "sparse_categorical_crossentropy",
                 feature_preprocessing: Optional[Callable] = None):
        super().__init__(model, criterion, feature_preprocessing)
        self.label_dtype = np.int32

    def _wrap_model(self) -> "NNClassifierModel":
        return NNClassifierModel(
            self.model, feature_preprocessing=self.feature_preprocessing,
            features_col=self.features_col,
            prediction_col=self.prediction_col, batch_size=self.batch_size)


class NNClassifierModel(NNModel):
    def _postprocess(self, preds: np.ndarray) -> np.ndarray:
        from ...utils.prediction import probs_to_classes
        return probs_to_classes(preds)
