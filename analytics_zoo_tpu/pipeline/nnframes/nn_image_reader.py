"""NNImageReader — image files into the NNFrames columnar table, parity
with ``pipeline/nnframes/NNImageReader.scala`` (which reads image files
into a Spark DataFrame of image rows via OpenCV JNI).

TPU-native shape: the "image DataFrame" is the same dict-of-arrays table
NNFrames trains from — ``{"image": NHWC uint8, "path": origin files,
["label": int32]}`` — decoded on the host with PIL (the OpenCV-JNI role,
SURVEY §2.3) and resized to a common static shape so batches stack dense
for XLA.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...feature.image import ImageSet
from .nn_estimator import Table

__all__ = ["NNImageReader"]


class NNImageReader:
    """``NNImageReader.readImages(path, ...)`` equivalent."""

    @staticmethod
    def read_images(path: str, resize_h: int, resize_w: int,
                    with_label: bool = False) -> Table:
        """Read a file / directory / per-class directory tree into a table.

        A common ``(resize_h, resize_w)`` is REQUIRED (the reference keeps
        ragged mats and pays per-image work downstream; a dense NHWC column
        is the XLA-friendly contract).
        """
        iset = ImageSet.read(path, with_label=with_label,
                             resize_h=resize_h, resize_w=resize_w)
        images = (iset.images if isinstance(iset.images, np.ndarray)
                  else np.stack(iset.images))
        table: Table = {"image": images,
                        "path": np.asarray(iset.paths or [""] * len(iset))}
        if iset.labels is not None:
            table["label"] = iset.labels.astype(np.int32)
        return table
