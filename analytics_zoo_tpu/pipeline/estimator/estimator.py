"""Estimator — parity with the reference's
``pipeline/estimator/Estimator.scala:33-183``: a model + per-submodule
optimizers + gradient clipping, driving the shared training engine on a
``FeatureSet``, with checkpoint/end triggers and validation.

The reference's ``Estimator`` delegates to ``InternalDistriOptimizer``
(``Estimator.scala:118-155``); here it delegates to the same jitted
``TrainingLoop`` that backs ``KerasNet.fit`` — one engine, two facades, like
the reference (``Topology.scala`` vs ``Estimator.scala`` both driving
BigDL's DistriOptimizer).

``LocalEstimator`` (``pipeline/estimator/LocalEstimator.scala:39-48``) — the
reference's single-JVM thread-pool trainer — needs no separate engine here:
a single-process mesh IS the local mode. The class below keeps the
array-based ``fit(x, y)`` surface and delegates to the same loop.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import optax

from ...common.triggers import Trigger
from ...feature.feature_set import FeatureSet
from ..api.keras import metrics as metrics_lib
from ..api.keras import objectives
from ..api.keras import optimizers as optim_lib
from ..api.keras.engine import KerasNet
from ..api.keras.training import TrainingLoop

__all__ = ["Estimator", "LocalEstimator"]


class Estimator:
    """``Estimator(model, optimMethods, modelDir)``
    (``Estimator.scala:65-68``). ``optim_methods`` is a single optimizer
    spec (name / optax transform) or a dict mapping a layer-name prefix to
    one — the per-submodule split of ``Topology.scala:1122-1143``."""

    def __init__(self, model: KerasNet,
                 optim_methods: Union[str, optax.GradientTransformation,
                                      Dict[str, Any], None] = "adam",
                 model_dir: Optional[str] = None):
        self.model = model
        self.model_dir = model_dir
        self._optim_methods = optim_methods
        self._clip_value: Optional[float] = None
        self._clip_norm: Optional[float] = None
        self._loop: Optional[TrainingLoop] = None
        self._loop_key = None  # (criterion, validation_methods) the loop was built for
        self._last_criterion: Any = None

    # ---- clipping (Estimator.scala:75-100) --------------------------------
    def set_constant_gradient_clipping(self, min_v: float, max_v: float):
        """Symmetric constant clipping; the engine clips by absolute value so
        the bound is ``max(|min|, |max|)`` (optax.clip semantics)."""
        self._clip_value = max(abs(min_v), abs(max_v))
        self._loop = None
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float):
        self._clip_norm = clip_norm
        self._loop = None
        return self

    def clear_gradient_clipping(self):
        self._clip_value = self._clip_norm = None
        self._loop = None
        return self

    # ---- engine assembly --------------------------------------------------
    def _build_optimizer(self) -> optax.GradientTransformation:
        om = self._optim_methods
        if isinstance(om, dict):
            opt = optim_lib.multi_optimizer(om)
        else:
            opt = optim_lib.get_optimizer(om if om is not None else "adam")
        return optim_lib.with_clipping(opt, clip_norm=self._clip_norm,
                                      clip_value=self._clip_value)

    def _get_loop(self, criterion, validation_methods) -> TrainingLoop:
        """Build (or reuse) the engine loop. Reuse requires the SAME
        (criterion, validation_methods) specs — rebuilding needlessly would
        discard optimizer state across incremental ``train`` calls, while
        reusing across a criterion change would silently train on the old
        loss."""
        key = (criterion if isinstance(criterion, str) else id(criterion),
               tuple(m if isinstance(m, str) else id(m)
                     for m in (validation_methods or [])))
        if self._loop is not None and self._loop_key == key:
            return self._loop
        loss_fn = objectives.get_loss(criterion)
        ms = [metrics_lib.get_metric(m) for m in (validation_methods or [])]
        loop = TrainingLoop(self.model, self._build_optimizer(), loss_fn, ms)
        self._loop, self._loop_key = loop, key
        self.model._loop = loop  # evaluate/predict facades reuse it
        return loop

    # ---- train / evaluate (Estimator.scala:118-176) -----------------------
    def train(self, train_set: FeatureSet, criterion: Any = "mse", *,
              batch_size: int = 32, nb_epoch: int = 1,
              end_trigger: Optional[Trigger] = None,
              checkpoint_trigger: Optional[Trigger] = None,
              checkpoint_keep: Optional[int] = None,
              validation_set: Optional[FeatureSet] = None,
              validation_methods: Optional[Sequence[Any]] = None,
              callbacks: Sequence[Callable] = ()) -> Dict[str, List[float]]:
        """Train on a FeatureSet. Checkpoints go to ``model_dir`` on
        ``checkpoint_trigger`` (``Estimator.scala:118-155``) through the
        durable async checkpoint subsystem (``utils/checkpoint.py``:
        manifest-committed snapshots, verified resume with corruption
        fallback — see docs/guides/TRAINING.md), with the engine's
        retry-on-failure semantics. ``checkpoint_keep`` bounds retention
        (default: the ``zoo.checkpoint.keep`` conf; 0 keeps every
        snapshot)."""
        if not isinstance(train_set, FeatureSet):
            raise TypeError("train expects a FeatureSet; build one with "
                            "FeatureSet.array(...)")
        self._get_loop(criterion, validation_methods)
        self._last_criterion = criterion
        if self.model_dir is not None:
            self.model.set_checkpoint(self.model_dir,
                                      trigger=checkpoint_trigger,
                                      keep=checkpoint_keep)
        elif checkpoint_trigger is not None:
            import logging
            logging.getLogger("analytics_zoo_tpu.estimator").warning(
                "checkpoint_trigger given but Estimator has no model_dir — "
                "no snapshots will be written and a failure cannot resume")
        val = None
        if validation_set is not None:
            val = (validation_set.x, validation_set.y)
        return self._loop.fit_feature_set(
            train_set, batch_size=batch_size, nb_epoch=nb_epoch,
            validation_data=val, end_trigger=end_trigger, callbacks=callbacks)

    def evaluate(self, validation_set: FeatureSet,
                 validation_methods: Optional[Sequence[Any]] = None, *,
                 criterion: Any = None,
                 batch_size: int = 32) -> Dict[str, float]:
        """``criterion`` defaults to whatever ``train`` last used, so the
        reported loss matches the trained objective."""
        if criterion is None:
            criterion = (self._last_criterion
                         if self._last_criterion is not None else "mse")
        loop = self._get_loop(criterion, validation_methods)
        return loop.evaluate(validation_set.x, validation_set.y,
                             batch_size=batch_size)


class LocalEstimator(Estimator):
    """``LocalEstimator(model, criterion, optimMethod).fit(data, ...)``
    (``LocalEstimator.scala:39-48,89``) — the array-based single-host
    surface. The reference needs a dedicated thread-pool trainer because
    its distributed engine requires Spark; here local and distributed are
    the same jitted loop, so this is the ``fit(x, y)`` facade over
    :class:`Estimator`."""

    def __init__(self, model: KerasNet, criterion: Any = "mse",
                 optim_method: Union[str, optax.GradientTransformation,
                                     None] = "adam"):
        super().__init__(model, optim_methods=optim_method)
        self.criterion = criterion

    def fit(self, x, y, *, batch_size: int = 32, nb_epoch: int = 1,
            validation_data=None,
            validation_methods: Optional[Sequence[Any]] = None,
            callbacks: Sequence[Callable] = ()) -> Dict[str, List[float]]:
        val = (FeatureSet.array(*validation_data)
               if (validation_data is not None
                   and not isinstance(validation_data, FeatureSet))
               else validation_data)
        return self.train(FeatureSet.array(x, y),
                          criterion=self.criterion, batch_size=batch_size,
                          nb_epoch=nb_epoch, validation_set=val,
                          validation_methods=validation_methods,
                          callbacks=callbacks)
