"""Estimator facade (``pipeline/estimator`` of the reference, L4)."""

from .estimator import Estimator, LocalEstimator  # noqa: F401

__all__ = ["Estimator", "LocalEstimator"]
