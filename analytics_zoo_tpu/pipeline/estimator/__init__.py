"""Estimator facade (``pipeline/estimator`` of the reference, L4)."""

from .estimator import Estimator

__all__ = ["Estimator"]
