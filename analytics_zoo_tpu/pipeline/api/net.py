"""Net — the unified model-import facade (parity with
``pipeline/api/Net.scala:123-171``: ``Net.load`` / ``loadBigDL`` /
``loadCaffe`` / ``loadTF`` / ``loadTorch``) plus the ``TorchNet`` role
(``pipeline/api/net/TorchNet.scala``).

The reference keeps foreign models foreign (TorchScript/libtensorflow
sessions behind JNI); the TPU-native design converts them into native
layers instead, so every import is jittable, shardable, and fine-tunable
under the one training engine. ``TorchNet.from_module`` maps the common
``torch.nn`` module types onto native layers with weights translated
(Linear kernels transpose to (in, out); Conv2d OIHW kernels to HWIO with
an NCHW→NHWC adapter at the graph edges, like the Caffe importer).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from .keras.engine import Input, KerasNet, Lambda, Model
from .keras.layers import (Activation, BatchNormalization, Convolution2D,
                           Dense, Dropout, Embedding, Flatten, LayerNorm,
                           LeakyReLU, ZeroPadding2D)

__all__ = ["Net", "TorchNet", "TorchCriterion"]


def _np(t):
    return t.detach().cpu().numpy()


class TorchNet:
    """``TorchNet.from_module(torch_module, input_shape)`` — convert a
    torch module tree into a native graph with the pretrained weights
    installed. ``input_shape`` excludes the batch dim and uses the TORCH
    convention (e.g. ``(3, 224, 224)`` for images); image graphs run NHWC
    internally and accept NHWC input."""

    SUPPORTED = ("Sequential, Linear, Conv2d, BatchNorm1d/2d, LayerNorm, "
                 "Embedding, ReLU, LeakyReLU, Sigmoid, Tanh, Softmax, "
                 "GELU, MaxPool2d, AvgPool2d, AdaptiveAvgPool2d(1), "
                 "Flatten, Dropout, Identity")

    @staticmethod
    def _is(m, cls) -> bool:
        """isinstance that also recognizes TorchScript RecursiveScriptModules
        by their ``original_name`` (torch.jit.script preserves the
        ``__constants__`` attributes the converters read; traced modules
        lose them — see ``from_torchscript``)."""
        if isinstance(cls, tuple):
            return any(TorchNet._is(m, c) for c in cls)
        if isinstance(m, cls):
            return True
        return getattr(m, "original_name", None) == cls.__name__

    @staticmethod
    def from_module(module, input_shape: Sequence[int]) -> KerasNet:
        import torch.nn as nn

        mods = (list(module.children())
                if TorchNet._is(module, nn.Sequential) else [module])
        mods = TorchNet._flatten(mods, nn)

        shape = tuple(int(d) for d in input_shape)
        is_image = len(shape) == 3
        if is_image:
            c, h, w = shape
            inp = Input(shape=(h, w, c), name="input")
        else:
            inp = Input(shape=shape, name="input")
        x = inp
        # best-effort torch-convention shape (sans batch) threaded through
        # the conversion: conv/pool arithmetic, flatten order, and axis
        # decisions (BatchNorm1d, Softmax) all need it
        tshape: Optional[tuple] = shape

        for i, m in enumerate(mods):
            name = f"torch{i}_{type(m).__name__.lower()}"
            x, tshape = TorchNet._convert(m, x, name, tshape, nn)
        return Model(input=inp, output=x)

    @staticmethod
    def from_torchscript(path_or_module,
                         input_shape: Sequence[int]) -> KerasNet:
        """Load a ``torch.jit.save``d module file and convert it
        (``TorchNet.scala:39`` role — the reference executes serialized
        TorchScript via libtorch JNI; here the module tree converts to
        native layers like ``from_module``, so the import jits/shards/
        fine-tunes).

        Works with ``torch.jit.script``-ed modules (scripting preserves
        the ``__constants__`` attributes — kernel sizes, strides, eps —
        the converters read). ``torch.jit.trace``-d modules drop those
        attributes into the graph; they fail with a clear message."""
        import os
        import torch

        m = path_or_module
        if isinstance(m, (str, bytes)):
            m = torch.jit.load(os.fsdecode(m), map_location="cpu")
        try:
            return TorchNet.from_module(m, input_shape)
        except AttributeError as e:
            raise NotImplementedError(
                f"TorchScript module is missing a converter attribute "
                f"({e}) — traced modules lose their __constants__; "
                f"re-export with torch.jit.script, or pass the live "
                f"nn.Module") from e

    @staticmethod
    def _flatten(mods, nn) -> List[Any]:
        out = []
        for m in mods:
            if TorchNet._is(m, nn.Sequential):
                out.extend(TorchNet._flatten(list(m.children()), nn))
            else:
                out.append(m)
        return out

    # -- per-module conversion ---------------------------------------------
    @staticmethod
    def _convert(m, x, name, tshape, nn):
        if TorchNet._is(m, nn.Linear):
            layer = Dense(m.out_features, bias=m.bias is not None, name=name)
            w = {"W": _np(m.weight).T}
            if m.bias is not None:
                w["b"] = _np(m.bias)
            layer._pretrained = w
            return layer(x), (m.out_features,)
        if TorchNet._is(m, nn.Conv2d):
            if m.groups != 1:
                raise NotImplementedError(f"{name}: grouped torch Conv2d")
            if m.padding_mode != "zeros":
                raise NotImplementedError(
                    f"{name}: padding_mode={m.padding_mode!r} (only zeros)")
            ph, pw = (m.padding if isinstance(m.padding, tuple)
                      else (m.padding, m.padding))
            if isinstance(ph, str):
                raise NotImplementedError(f"{name}: string padding mode")
            if (ph, pw) != (0, 0):
                x = ZeroPadding2D((ph, pw), name=f"{name}_pad")(x)
            layer = Convolution2D(
                m.out_channels, m.kernel_size[0], m.kernel_size[1],
                subsample=tuple(m.stride), border_mode="valid",
                dilation=tuple(m.dilation), bias=m.bias is not None,
                name=name)
            w = {"W": np.transpose(_np(m.weight), (2, 3, 1, 0))}
            if m.bias is not None:
                w["b"] = _np(m.bias)
            layer._pretrained = w
            if tshape is not None and len(tshape) == 3:
                c, h, wd = tshape
                h2 = (h + 2 * ph - m.dilation[0] * (m.kernel_size[0] - 1)
                      - 1) // m.stride[0] + 1
                w2 = (wd + 2 * pw - m.dilation[1] * (m.kernel_size[1] - 1)
                      - 1) // m.stride[1] + 1
                tshape = (m.out_channels, h2, w2)
            else:
                tshape = None
            return layer(x), tshape
        if TorchNet._is(m, (nn.BatchNorm1d, nn.BatchNorm2d)):
            if not m.track_running_stats:
                raise NotImplementedError(
                    f"{name}: BatchNorm(track_running_stats=False) has no "
                    f"inference-time statistics to import")
            # BatchNorm1d over a (N, C, L) stream normalizes axis 1; on a
            # 2D (N, C) stream the channel axis IS the last axis. Image
            # streams run NHWC here, so BatchNorm2d normalizes -1.
            axis = 1 if (TorchNet._is(m, nn.BatchNorm1d) and tshape is not None
                         and len(tshape) == 2) else -1
            layer = BatchNormalization(epsilon=m.eps, axis=axis,
                                       scale=m.affine, center=m.affine,
                                       name=name)
            if m.affine:
                layer._pretrained = {"gamma": _np(m.weight),
                                     "beta": _np(m.bias)}
            layer._pretrained_state = {"moving_mean": _np(m.running_mean),
                                       "moving_var": _np(m.running_var)}
            return layer(x), tshape
        if TorchNet._is(m, nn.LayerNorm):
            layer = LayerNorm(epsilon=m.eps, name=name)
            if m.elementwise_affine:
                layer._pretrained = {"gamma": _np(m.weight),
                                     "beta": _np(m.bias)}
            return layer(x), tshape
        if TorchNet._is(m, nn.Embedding):
            layer = Embedding(m.num_embeddings, m.embedding_dim, name=name)
            layer._pretrained = {"embeddings": _np(m.weight)}
            return layer(x), (tshape + (m.embedding_dim,)
                              if tshape is not None else None)
        if TorchNet._is(m, nn.ReLU):
            return Activation("relu", name=name)(x), tshape
        if TorchNet._is(m, nn.LeakyReLU):
            return LeakyReLU(m.negative_slope, name=name)(x), tshape
        if TorchNet._is(m, nn.Sigmoid):
            return Activation("sigmoid", name=name)(x), tshape
        if TorchNet._is(m, nn.Tanh):
            return Activation("tanh", name=name)(x), tshape
        if TorchNet._is(m, nn.Softmax):
            # native softmax runs over the LAST axis; reject anything else
            last = len(tshape) if tshape is not None else None
            if m.dim not in (-1, last):
                raise NotImplementedError(
                    f"{name}: Softmax(dim={m.dim}) — only the last axis "
                    f"maps onto the native layer")
            return Activation("softmax", name=name)(x), tshape
        if TorchNet._is(m, nn.GELU):
            import jax
            approx = getattr(m, "approximate", "none") == "tanh"
            return Lambda(lambda t, a=approx: jax.nn.gelu(t, approximate=a),
                          name=name)(x), tshape
        if TorchNet._is(m, nn.MaxPool2d) or TorchNet._is(m, nn.AvgPool2d):
            from .keras.layers import AveragePooling2D, MaxPooling2D
            k = (m.kernel_size if isinstance(m.kernel_size, tuple)
                 else (m.kernel_size, m.kernel_size))
            s = (m.stride if isinstance(m.stride, tuple)
                 else (m.stride or m.kernel_size,) * 2)
            p = (m.padding if isinstance(m.padding, tuple)
                 else (m.padding, m.padding))
            if getattr(m, "ceil_mode", False):
                raise NotImplementedError(f"{name}: ceil_mode pooling")
            if getattr(m, "dilation", 1) not in (1, (1, 1)):
                raise NotImplementedError(f"{name}: dilated pooling")
            if getattr(m, "return_indices", False):
                raise NotImplementedError(f"{name}: return_indices pooling")
            if TorchNet._is(m, nn.AvgPool2d) and not m.count_include_pad:
                raise NotImplementedError(
                    f"{name}: AvgPool2d(count_include_pad=False)")
            if p != (0, 0):
                # zero-pad + valid pool = torch floor-mode semantics with
                # count_include_pad=True (the torch default)
                x = ZeroPadding2D(p, name=f"{name}_pad")(x)
            pool_cls = (MaxPooling2D if TorchNet._is(m, nn.MaxPool2d)
                        else AveragePooling2D)
            node = pool_cls(k, strides=s, border_mode="valid", name=name)(x)
            if tshape is not None and len(tshape) == 3:
                c, h, w = tshape
                tshape = (c, (h + 2 * p[0] - k[0]) // s[0] + 1,
                          (w + 2 * p[1] - k[1]) // s[1] + 1)
            else:
                tshape = None
            return node, tshape
        if TorchNet._is(m, nn.AdaptiveAvgPool2d):
            out_sz = m.output_size
            if out_sz not in (1, (1, 1)):
                raise NotImplementedError(f"{name}: adaptive pool to "
                                          f"{out_sz}")
            from .keras.layers import GlobalAveragePooling2D
            node = GlobalAveragePooling2D(name=name)(x)
            return node, ((tshape[0],) if tshape is not None
                          and len(tshape) == 3 else None)
        if TorchNet._is(m, nn.Flatten):
            if (m.start_dim, m.end_dim) != (1, -1):
                raise NotImplementedError(
                    f"{name}: Flatten(start_dim={m.start_dim}, "
                    f"end_dim={m.end_dim}) — only full flatten")
            if tshape is not None and len(tshape) == 3:
                # torch flattens NCHW C*H*W order: transpose first so the
                # following Linear's pretrained weights line up
                import jax.numpy as jnp
                x = Lambda(lambda t: jnp.transpose(t, (0, 3, 1, 2)),
                           name=f"{name}_nchw")(x)
            flat = (int(np.prod(tshape)),) if tshape is not None else None
            return Flatten(name=name)(x), flat
        if TorchNet._is(m, nn.Dropout):
            return Dropout(m.p, name=name)(x), tshape
        if TorchNet._is(m, nn.Identity):
            return x, tshape
        raise NotImplementedError(
            f"torch module {type(m).__name__} not supported; supported: "
            f"{TorchNet.SUPPORTED}")


def _install_pretrained(model: KerasNet) -> KerasNet:
    """After build, copy stashed ``_pretrained`` weights into the param
    tree (and running stats into net_state), shape-checked."""
    import jax.numpy as jnp
    model.init_weights()
    for node in model._topo:
        layer = node.layer
        lname = layer.name
        w = getattr(layer, "_pretrained", None)
        if w is not None:
            tmpl = model.params.get(lname)
            if tmpl is None:
                raise ValueError(f"pretrained weights for unknown layer "
                                 f"{lname!r}")
            for k, v in w.items():
                if np.shape(tmpl[k]) != np.shape(v):
                    raise ValueError(
                        f"{lname}.{k}: torch weight shape {np.shape(v)} vs "
                        f"graph {np.shape(tmpl[k])}")
            model.params[lname] = {k: jnp.asarray(v) for k, v in w.items()}  # zoolint: disable=ZL009 one-time load; per-layer shapes differ, nothing to batch
        s = getattr(layer, "_pretrained_state", None)
        if s is not None:
            model.net_state[lname] = {k: jnp.asarray(v)  # zoolint: disable=ZL009 one-time load; per-layer shapes differ
                                      for k, v in s.items()}
    return model


class Net:
    """Unified loader facade (``Net.scala:123-171``)."""

    @staticmethod
    def load(path: str):
        """A model saved by this framework (ZooModel ``.npz``)."""
        from ...models.common.zoo_model import load_model
        return load_model(path)

    @staticmethod
    def load_caffe(model_path: str,
                   input_shape: Optional[Sequence[int]] = None) -> KerasNet:
        from ...models.caffe import load_caffe
        return load_caffe(model_path, input_shape)

    @staticmethod
    def load_onnx(path: str):
        from .onnx import load_onnx
        return load_onnx(path)

    @staticmethod
    def load_torch(module, input_shape: Sequence[int]) -> KerasNet:
        """An in-memory ``torch.nn`` module OR a TorchScript file path
        (``Net.loadTorch`` / ``TorchNet.scala:39`` — the reference loads
        serialized TorchScript; scripted files convert here too)."""
        if isinstance(module, (str, bytes)):
            model = TorchNet.from_torchscript(module, input_shape)
        else:
            model = TorchNet.from_module(module, input_shape)
        return _install_pretrained(model)

    @staticmethod
    def load_tf(path: str, inputs=None, outputs=None, trainable: bool = True,
                **kwargs):
        """A frozen TF GraphDef ``.pb`` (``Net.loadTF``,
        ``Net.scala:123-171``) or a SavedModel DIRECTORY
        (``TFNetForInference.scala:412`` role: graph + restored variables,
        fine-tunable) — executed as jitted JAX ops, no TF runtime; see
        ``tfnet.py`` / ``saved_model.py``."""
        import os
        if os.path.isdir(path):
            from .saved_model import load_saved_model
            return load_saved_model(path, inputs=inputs, outputs=outputs,
                                    trainable=trainable, **kwargs)
        if kwargs:
            raise TypeError(f"unexpected arguments for a frozen GraphDef "
                            f"file: {sorted(kwargs)} (signature/tags apply "
                            f"to SavedModel directories only)")
        from .tfnet import load_tf
        return load_tf(path, inputs=inputs, outputs=outputs,
                       trainable=trainable)


class TorchCriterion:
    """``TorchCriterion.scala`` role — bring a torch LOSS into compile().

    The reference executes the torch loss via JNI each step; here the loss
    TRANSLATES onto native jax math once (so it jits into the train step):
    pass a ``torch.nn`` loss module, its class name, or a TorchScript file
    of one. Supported: MSELoss, L1Loss, SmoothL1Loss, CrossEntropyLoss
    (logits + int labels), NLLLoss (log-probs + int labels), BCELoss,
    BCEWithLogitsLoss — ``reduction`` mean/sum matches torch exactly
    (mean/sum over ELEMENTS for the elementwise losses, over examples for
    the class-indexed ones).

    >>> model.compile(optimizer="adam", loss=TorchCriterion(nn.MSELoss()))
    """

    def __init__(self, loss):
        import os
        if isinstance(loss, bytes):
            loss = loss.decode()
        if isinstance(loss, str) and (loss.endswith((".pt", ".pth"))
                                      or os.path.exists(loss)):
            import torch
            loss = torch.jit.load(loss, map_location="cpu")
        name = (loss if isinstance(loss, str)
                else getattr(loss, "original_name", None)
                or type(loss).__name__)
        reduction = getattr(loss, "reduction", "mean")
        if reduction not in ("mean", "sum"):
            raise NotImplementedError(
                f"TorchCriterion: reduction={reduction!r} (mean|sum)")
        # options the translation does NOT carry must refuse, not silently
        # train a different objective than the torch loss handed in
        for attr, neutral in (("weight", None), ("pos_weight", None),
                              ("ignore_index", -100),
                              ("label_smoothing", 0.0)):
            val = getattr(loss, attr, neutral)
            non_neutral = (val is not None if neutral is None
                           else val is not None and float(val) != neutral)
            if non_neutral:
                raise NotImplementedError(
                    f"TorchCriterion: {name}({attr}={val!r}) is not "
                    f"translated; drop the option or use a native loss")
        table = {
            "MSELoss": self._mse,
            "L1Loss": self._l1,
            "SmoothL1Loss": self._smooth_l1(getattr(loss, "beta", 1.0)),
            "CrossEntropyLoss": self._ce_from_logits,
            "NLLLoss": self._nll,
            "BCELoss": self._bce,
            "BCEWithLogitsLoss": self._bce_logits,
        }
        if name not in table:
            raise NotImplementedError(
                f"TorchCriterion: unsupported torch loss {name!r}; "
                f"supported: {sorted(table)}")
        self.name = name
        self.reduction = reduction
        self._unreduced = table[name]
        # evaluate() masks padded tail rows through the per_example form
        # (objectives.get_loss contract); mean over non-batch axes — for
        # reduction="sum" the scalar form still sums (torch semantics),
        # only the masked per-row statistic uses this
        import jax.numpy as jnp

        def per_example(y_true, y_pred):
            un = self._unreduced(y_true, y_pred)
            if un.ndim <= 1:
                return un
            return jnp.mean(un.reshape(un.shape[0], -1), axis=-1)

        self.per_example = per_example

    # -- unreduced forms ----------------------------------------------------
    @staticmethod
    def _mse(yt, yp):
        return (yp - yt.astype(yp.dtype)) ** 2

    @staticmethod
    def _l1(yt, yp):
        import jax.numpy as jnp
        return jnp.abs(yp - yt.astype(yp.dtype))

    @staticmethod
    def _smooth_l1(beta):
        import jax.numpy as jnp
        if beta == 0.0:          # torch documents beta=0 as exactly L1
            return TorchCriterion._l1

        def fn(yt, yp):
            d = jnp.abs(yp - yt.astype(yp.dtype))
            # both where-branches are differentiated: keep the untaken
            # quadratic branch finite at d==0 via the safe denominator
            return jnp.where(d < beta, 0.5 * d ** 2 / beta,
                             d - 0.5 * beta)
        return fn

    @staticmethod
    def _bce(yt, yp):
        import jax.numpy as jnp
        ytf = yt.astype(yp.dtype)
        # torch BCELoss clamps the LOG terms at -100 (not the probability)
        logp = jnp.maximum(jnp.log(jnp.maximum(yp, 0.0)), -100.0)
        log1mp = jnp.maximum(jnp.log(jnp.maximum(1 - yp, 0.0)), -100.0)
        return -(ytf * logp + (1 - ytf) * log1mp)

    @staticmethod
    def _bce_logits(yt, yp):
        import jax.numpy as jnp
        ytf = yt.astype(yp.dtype)
        return (jnp.maximum(yp, 0) - yp * ytf
                + jnp.log1p(jnp.exp(-jnp.abs(yp))))

    @staticmethod
    def _ce_from_logits(yt, yp):
        import jax
        import jax.numpy as jnp
        # imported-net classifier heads: class-count logits, not LM
        # vocab — full log-probs are KBs here, fusion buys nothing
        logp = jax.nn.log_softmax(yp, axis=-1)  # zoolint: disable=ZL012 small-class imported-net head
        return -jnp.take_along_axis(
            logp, yt.astype(jnp.int32).reshape(-1, 1), axis=-1)[:, 0]

    @staticmethod
    def _nll(yt, yp):
        import jax.numpy as jnp
        return -jnp.take_along_axis(
            yp, yt.astype(jnp.int32).reshape(-1, 1), axis=-1)[:, 0]

    def __call__(self, y_true, y_pred):
        import jax.numpy as jnp
        un = self._unreduced(y_true, y_pred)
        return jnp.sum(un) if self.reduction == "sum" else jnp.mean(un)

