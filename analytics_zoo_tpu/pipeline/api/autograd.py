"""Autograd op library + CustomLoss — parity with
``pipeline/api/autograd/math.scala:32-365`` and ``CustomLoss.scala``.

The reference builds BigDL graph nodes per op; here each op is a ``Lambda``
graph node over the package's ``Variable`` handles, so an autograd expression
IS a Keras graph — it jits, shards, and serializes like any model. Ops accept
``Variable`` or plain constants (broadcast like the reference's scalars).

``CustomLoss`` turns an autograd expression over (y_true, y_pred) into a loss
callable usable directly in ``compile(loss=...)`` — the jitted train step
traces straight through it (no py4j round-trip analogue to pay).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .keras.engine import Input, Lambda, Model, Variable, unique_name

__all__ = ["abs", "sum", "clip", "square", "sqrt", "maximum", "mean", "log",
           "epsilon", "exp", "pow", "softsign", "softplus", "stack",
           "expand_dims", "contiguous", "mm", "l2_normalize", "batch_dot",
           "erf", "CustomLoss"]

def _unary(v: Variable, fn: Callable, name: str) -> Variable:
    return Lambda(fn, name=unique_name(name + "_"))(v)


def _binary(a, b, fn: Callable, name: str) -> Variable:
    if isinstance(a, Variable) and isinstance(b, Variable):
        return Lambda(fn, name=unique_name(name + "_"))([a, b])
    if isinstance(a, Variable):
        return Lambda(lambda x: fn(x, b), name=unique_name(name + "_"))(a)
    return Lambda(lambda x: fn(a, x), name=unique_name(name + "_"))(b)


def abs(v):  # noqa: A001 — mirrors the reference's op name
    return _unary(v, jnp.abs, "abs")


def sum(v, axis: int = 0, keep_dims: bool = False):  # noqa: A001
    return _unary(v, lambda a: jnp.sum(a, axis=axis, keepdims=keep_dims),
                  "sum")


def mean(v, axis: int = 0, keep_dims: bool = False):
    return _unary(v, lambda a: jnp.mean(a, axis=axis, keepdims=keep_dims),
                  "mean")


def clip(v, min: float, max: float):  # noqa: A002
    return _unary(v, lambda a: jnp.clip(a, min, max), "clip")


def square(v):
    return _unary(v, jnp.square, "square")


def sqrt(v):
    return _unary(v, jnp.sqrt, "sqrt")


def log(v):
    return _unary(v, jnp.log, "log")


def exp(v):
    return _unary(v, jnp.exp, "exp")


def erf(v):
    return _unary(v, jax.scipy.special.erf, "erf")


def softsign(v):
    return _unary(v, lambda a: a / (1.0 + jnp.abs(a)), "softsign")


def softplus(v):
    return _unary(v, jax.nn.softplus, "softplus")


def maximum(a, b):
    return _binary(a, b, jnp.maximum, "maximum")


def pow(v, a: float):  # noqa: A001
    return _unary(v, lambda x: jnp.power(x, a), "pow")


def epsilon() -> float:
    """``AutoGrad.epsilon`` — the fuzz constant."""
    return 1e-7


def stack(inputs: Sequence[Variable], axis: int = 1) -> Variable:
    """``stack(inputs, axis)`` — join along a NEW axis (reference default
    axis=1, after batch)."""
    return Lambda(lambda *xs: jnp.stack(xs, axis=axis),
                  name=unique_name("stack_"))(list(inputs))


def expand_dims(v, axis: int):
    return _unary(v, lambda a: jnp.expand_dims(a, axis=axis), "expanddims")


def contiguous(v):
    """Layout no-op (XLA owns layout); kept for API parity."""
    return _unary(v, lambda a: a, "contiguous")


def mm(x, y, axes: Optional[Tuple[int, int]] = None):
    """``mm(x, y, axes)`` — batched matmul contracting ``axes``
    (``math.scala`` mm; default contracts x's last with y's first non-batch)."""
    if axes is None:
        return _binary(
            x, y, lambda a, b: jnp.matmul(
                a, b, preferred_element_type=jnp.float32).astype(a.dtype),
            "mm")

    def f(a, b):
        return jnp.tensordot(a, b, axes=(axes[0], axes[1]),
                             preferred_element_type=jnp.float32).astype(a.dtype)
    return _binary(x, y, f, "mm")


def batch_dot(x, y, axes: Tuple[int, int] = (2, 2), normalize: bool = False):
    """``batchDot(x, y, axes, normalize)`` — per-sample contraction (the
    KNRM translation-matrix op); ``normalize`` l2-normalizes along the
    contracted axes first (cosine similarity)."""

    def f(a, b):
        if normalize:
            a = a / jnp.maximum(jnp.linalg.norm(a, axis=axes[0],
                                                keepdims=True), 1e-12)
            b = b / jnp.maximum(jnp.linalg.norm(b, axis=axes[1],
                                                keepdims=True), 1e-12)
        # axes count the batch dim (reference convention); contract
        # per-sample via vmap'd tensordot
        td = lambda aa, bb: jnp.tensordot(  # noqa: E731
            aa, bb, axes=((axes[0] - 1,), (axes[1] - 1,)),
            preferred_element_type=jnp.float32)
        return jax.vmap(td)(a, b).astype(a.dtype)

    return _binary(x, y, f, "batchdot")


def l2_normalize(v, axis: int):
    return _unary(
        v, lambda a: a / jnp.maximum(jnp.linalg.norm(a, axis=axis,
                                                     keepdims=True), 1e-12),
        "l2normalize")


class CustomLoss:
    """``CustomLoss.scala`` — a loss defined as an autograd expression.

    >>> def rmse(y_true, y_pred):
    ...     return A.sqrt(A.mean(A.square(y_true - y_pred), axis=1))
    >>> model.compile(optimizer="adam", loss=CustomLoss(rmse, (1,)))

    ``loss_fn(y_true, y_pred)`` receives Variables of shape
    ``(batch,) + y_shape`` and returns a per-sample (or scalar) Variable;
    the final loss is its mean.
    """

    def __init__(self, loss_fn: Callable[[Variable, Variable], Variable],
                 y_pred_shape: Tuple[int, ...],
                 y_true_shape: Optional[Tuple[int, ...]] = None):
        yt = Input(shape=tuple(y_true_shape or y_pred_shape))
        yp = Input(shape=tuple(y_pred_shape))
        out = loss_fn(yt, yp)
        if not isinstance(out, Variable):
            raise TypeError("loss_fn must return an autograd Variable")
        self._graph = Model([yt, yp], out)
        self._params = self._graph.build(jax.random.key(0), None)

    def __call__(self, y_true, y_pred):
        y = self._graph.call(self._params, [y_true, y_pred])
        return jnp.mean(y)
