"""Training engine — the TPU-native replacement for the reference's
``InternalDistriOptimizer`` (``Topology.scala:1062-1540``) and the
``compile/fit/evaluate/predict`` facade (``Topology.scala:135,343,418,496``).

Architecture (vs the reference's per-iteration Spark jobs + BlockManager
parameter-server allreduce, ``wp-bigdl.md:113-160``):

* ONE jitted ``train_step`` — forward, backward, optimizer update — traced
  once, compiled by XLA, and run per minibatch with donated buffers.
* Data parallelism = batch sharded over the mesh ``data`` axis
  (``NamedSharding``); params replicated. XLA GSPMD inserts the gradient
  psum over ICI — there is no separate communication runtime to operate.
* Failure handling keeps the reference's semantics
  (``Topology.scala:1171-1253``): on a step failure, reload the latest
  checkpoint and retry, bounded by ``zoo.failure.retry_times``.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ....common.context import get_zoo_context
from ....common.triggers import (EveryEpoch, MaxEpoch, TrainLoopState, Trigger)
from ....parallel import mesh as mesh_lib
from . import metrics as metrics_lib
from . import objectives, optimizers as optim_lib
from .engine import KerasNet

log = logging.getLogger("analytics_zoo_tpu.training")


class CompiledSpec:
    def __init__(self, optimizer, loss, metrics):
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics


# ---------------------------------------------------------------------------
# data iteration helpers
# ---------------------------------------------------------------------------

def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _num_examples(x) -> int:
    return _as_list(x)[0].shape[0]


def _take(x, idx):
    xs = [np.asarray(a)[idx] for a in _as_list(x)]
    return xs if len(xs) > 1 else xs[0]


def iter_batches(x, y, batch_size: int, *, shuffle: bool, seed: int,
                 drop_last: bool):
    """Host-side minibatch iterator over numpy arrays. The FeatureSet layer
    provides richer iterators; this covers the plain ``fit(x, y)`` path."""
    n = _num_examples(x)
    order = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(order)
    end = n - (n % batch_size) if drop_last else n
    for i in range(0, end, batch_size):
        idx = order[i:i + batch_size]
        yield _take(x, idx), (None if y is None else _take(y, idx))


def shard_batch(batch, mesh=None):
    """Place a host batch onto the mesh, split over the data axis."""
    sharding = mesh_lib.batch_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), sharding), batch)


def _pad_to(x, size: int):
    xs = _as_list(x)
    out = []
    for a in xs:
        a = np.asarray(a)
        pad = size - a.shape[0]
        if pad > 0:
            a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0)
        out.append(a)
    return out if len(out) > 1 else out[0]


# ---------------------------------------------------------------------------
# The training loop (InternalDistriOptimizer / LocalOptimizer unified)
# ---------------------------------------------------------------------------

class TrainingLoop:
    """Owns the jitted step functions for one (model, optimizer, loss) triple."""

    def __init__(self, model: KerasNet, optimizer: optax.GradientTransformation,
                 loss: Callable, metrics: Sequence[metrics_lib.Metric] = ()):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = list(metrics)
        self.mesh = mesh_lib.global_mesh()
        self._train_step = None
        self._eval_step = None
        self._predict_step = None

    # -- jitted steps -------------------------------------------------------
    def build_train_step(self):
        model, opt, loss_fn = self.model, self.optimizer, self.loss

        def step(params, opt_state, net_state, rng, x, y):
            def lfn(p):
                yp, ns = model.apply(p, net_state, x, training=True, rng=rng)
                return loss_fn(y, yp), ns
            (l, ns), grads = jax.value_and_grad(lfn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, ns, l

        self._train_step = jax.jit(step, donate_argnums=(0, 1, 2))
        return self._train_step

    def build_eval_step(self):
        model, loss_fn, metrics = self.model, self.loss, self.metrics

        def step(params, net_state, x, y):
            yp, _ = model.apply(params, net_state, x, training=False, rng=None)
            stats = {m.name: m.update(y, yp) for m in metrics}
            stats["loss"] = {"sum": loss_fn(y, yp) * _first_dim(x),
                            "count": jnp.asarray(_first_dim(x), jnp.float32)}
            return stats

        self._eval_step = jax.jit(step)
        return self._eval_step

    def build_predict_step(self):
        model = self.model

        def step(params, net_state, x):
            yp, _ = model.apply(params, net_state, x, training=False, rng=None)
            return yp

        self._predict_step = jax.jit(step)
        return self._predict_step

    # -- loops --------------------------------------------------------------
    def fit(self, x, y, *, batch_size: int, nb_epoch: int,
            validation_data=None, rng=None,
            callbacks: Sequence[Callable[[Dict[str, Any]], None]] = (),
            shuffle: bool = True) -> Dict[str, List[float]]:
        ctx = get_zoo_context()
        model = self.model
        if model.params is None:
            model.init_weights(rng=rng, sample_input=_take(x, np.arange(1)))
        if self._train_step is None:
            self.build_train_step()

        params = jax.device_put(model.params, mesh_lib.replicated_sharding(self.mesh))
        net_state = jax.device_put(model.net_state, mesh_lib.replicated_sharding(self.mesh))
        opt_state = (model.opt_state if model.opt_state is not None
                     else self.optimizer.init(params))
        opt_state = jax.device_put(opt_state, mesh_lib.replicated_sharding(self.mesh))

        base_rng = rng if rng is not None else ctx.rng()
        history: Dict[str, List[float]] = {"loss": []}
        loop_state = TrainLoopState(iteration=model.finished_iterations,
                                    epoch=model.finished_epochs + 1)

        for epoch in range(model.finished_epochs + 1,
                           model.finished_epochs + nb_epoch + 1):
            t0 = time.time()
            losses = []
            n_seen = 0
            for bx, by in iter_batches(x, y, batch_size, shuffle=shuffle,
                                       seed=ctx.seed + epoch, drop_last=True):
                step_rng = jax.random.fold_in(base_rng, loop_state.iteration)
                bx_d, by_d = shard_batch((bx, by), self.mesh)
                params, opt_state, net_state, l = self._train_step(
                    params, opt_state, net_state, step_rng, bx_d, by_d)
                losses.append(l)
                n_seen += batch_size
                loop_state.iteration += 1
            epoch_loss = float(jnp.mean(jnp.stack(losses))) if losses else float("nan")
            dt = time.time() - t0
            history["loss"].append(epoch_loss)
            loop_state.epoch = epoch
            loop_state.epoch_finished = True

            record = {"epoch": epoch, "loss": epoch_loss,
                      "iteration": loop_state.iteration,
                      "throughput": n_seen / dt if dt > 0 else 0.0,
                      "params": params, "opt_state": opt_state,
                      "net_state": net_state, "loop_state": loop_state}
            if validation_data is not None:
                # publish latest weights for eval
                model.params, model.net_state = params, net_state
                val = self.evaluate(validation_data[0], validation_data[1],
                                    batch_size=batch_size)
                for k, v in val.items():
                    history.setdefault("val_" + k, []).append(v)
                record.update({"val_" + k: v for k, v in val.items()})
            log.info("Epoch %d: loss=%.6f (%.1f ex/s)%s", epoch, epoch_loss,
                     record["throughput"],
                     "".join(f" val_{k}={v:.4f}" for k, v in
                             (val.items() if validation_data is not None else ())))
            for cb in callbacks:
                cb(record)
            loop_state.epoch_finished = False

        model.params = params
        model.net_state = net_state
        model.opt_state = opt_state
        model.finished_epochs = epoch
        model.finished_iterations = loop_state.iteration
        return history

    def evaluate(self, x, y, *, batch_size: int = 32) -> Dict[str, float]:
        model = self.model
        if self._eval_step is None:
            self.build_eval_step()
        totals = None
        dp = mesh_lib.data_parallel_size(self.mesh)
        eff_bs = max(batch_size, dp)
        for bx, by in iter_batches(x, y, eff_bs, shuffle=False, seed=0,
                                   drop_last=False):
            n = _num_examples(bx)
            if n % dp != 0:
                padded = ((n + dp - 1) // dp) * dp
                bx, by = _pad_to(bx, padded), _pad_to(by, padded)
                # padding inflates counts slightly; acceptable for parity with
                # the reference, which also pads the tail minibatch
            bx_d, by_d = shard_batch((bx, by), self.mesh)
            stats = self._eval_step(model.params, model.net_state, bx_d, by_d)
            stats = jax.device_get(stats)
            totals = stats if totals is None else jax.tree.map(
                lambda a, b: a + b, totals, stats)
        out = {}
        if totals is None:
            return out
        for m in self.metrics:
            out[m.name] = float(m.finalize(totals[m.name]))
        out["loss"] = float(totals["loss"]["sum"] / max(totals["loss"]["count"], 1.0))
        return out

    def predict(self, x, *, batch_size: int = 32):
        model = self.model
        if self._predict_step is None:
            self.build_predict_step()
        dp = mesh_lib.data_parallel_size(self.mesh)
        outs = []
        n_total = _num_examples(x)
        eff_bs = max(batch_size, dp)
        for bx, _ in iter_batches(x, None, eff_bs, shuffle=False, seed=0,
                                  drop_last=False):
            n = _num_examples(bx)
            padded = ((n + dp - 1) // dp) * dp
            if n != padded:
                bx = _pad_to(bx, padded)
            bx_d = shard_batch(bx, self.mesh)
            yp = self._predict_step(model.params, model.net_state, bx_d)
            yp = jax.device_get(yp)
            outs.append(jax.tree.map(lambda a: a[:n], yp))
        if not outs:
            return None
        return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *outs)


def _first_dim(x):
    if isinstance(x, (list, tuple)):
        return x[0].shape[0]
    return x.shape[0]


# ---------------------------------------------------------------------------
# KerasNet facade: compile / fit / evaluate / predict
# (attached here so engine.py stays free of optimizer machinery)
# ---------------------------------------------------------------------------

def _compile(self: KerasNet, optimizer="adam", loss="mse", metrics=None,
             clip_norm: Optional[float] = None,
             clip_value: Optional[float] = None, **opt_kwargs):
    """``KerasNet.compile`` (``Topology.scala:135``)."""
    opt = optim_lib.get_optimizer(optimizer, **opt_kwargs)
    opt = optim_lib.with_clipping(opt, clip_norm=clip_norm, clip_value=clip_value)
    loss_fn = objectives.get_loss(loss)
    ms = [metrics_lib.get_metric(m) for m in (metrics or [])]
    self._compiled = CompiledSpec(opt, loss_fn, ms)
    self._loop = TrainingLoop(self, opt, loss_fn, ms)
    return self


def _init_weights(self: KerasNet, rng=None, input_shape=None, sample_input=None):
    """Materialize params/state. Shape comes from (in order) explicit
    ``input_shape``, a ``sample_input`` batch, or the declared layer shapes."""
    ctx = get_zoo_context()
    rng = rng if rng is not None else ctx.rng()
    shape = input_shape
    if shape is None and sample_input is not None:
        xs = sample_input if isinstance(sample_input, (list, tuple)) else [sample_input]
        shapes = [(None,) + tuple(np.asarray(a).shape[1:]) for a in xs]
        shape = shapes if len(shapes) > 1 else shapes[0]
    if shape is None:
        shape = self.input_shape
    params = self.build(rng, shape)
    state = self.initial_state(shape)
    self.params = params
    self.net_state = state
    return self


def _fit(self: KerasNet, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
         validation_data=None, shuffle: bool = True, rng=None, callbacks=()):
    """``KerasNet.fit`` (``Topology.scala:418``). ``x`` may be an array, a
    list of arrays (multi-input), or a FeatureSet (then ``y=None``)."""
    if self._compiled is None:
        raise RuntimeError("call compile() before fit()")
    try:
        from ....feature.feature_set import FeatureSet  # local import, avoid cycle
    except ImportError:
        FeatureSet = None
    if FeatureSet is not None and isinstance(x, FeatureSet):
        return self._loop.fit_feature_set(x, batch_size=batch_size,
                                          nb_epoch=nb_epoch,
                                          validation_data=validation_data,
                                          rng=rng, callbacks=callbacks)
    return self._loop.fit(x, y, batch_size=batch_size, nb_epoch=nb_epoch,
                          validation_data=validation_data, shuffle=shuffle,
                          rng=rng, callbacks=callbacks)


def _evaluate(self: KerasNet, x, y=None, batch_size: int = 32):
    """``KerasNet.evaluate`` (``Topology.scala:496``)."""
    if self._compiled is None:
        raise RuntimeError("call compile() before evaluate()")
    if self.params is None:
        raise RuntimeError("no weights; fit() or init_weights() first")
    return self._loop.evaluate(x, y, batch_size=batch_size)


def _predict(self: KerasNet, x, batch_size: int = 32, distributed: bool = True):
    """``KerasNet.predict`` (``Topology.scala:343`` family)."""
    if self.params is None:
        raise RuntimeError("no weights; fit() or init_weights() first")
    if self._compiled is None:
        self._loop = TrainingLoop(self, optax.identity(), objectives.get_loss("mse"), [])
    return self._loop.predict(x, batch_size=batch_size)


def _predict_classes(self: KerasNet, x, batch_size: int = 32, zero_based: bool = True):
    """``predictClass`` (``Predictor.scala:210``)."""
    probs = self._predict(x, batch_size=batch_size)
    if probs.ndim > 1 and probs.shape[-1] > 1:
        cls = np.argmax(probs, axis=-1)
    else:
        cls = (np.asarray(probs).reshape(-1) > 0.5).astype(np.int32)
    return cls if zero_based else cls + 1


# state attributes
KerasNet.params = None
KerasNet.net_state = None
KerasNet.opt_state = None
KerasNet.finished_epochs = 0
KerasNet.finished_iterations = 0
KerasNet._loop = None

KerasNet.compile = _compile
KerasNet.init_weights = _init_weights
KerasNet.fit = _fit
KerasNet.evaluate = _evaluate
KerasNet.predict = _predict
KerasNet.predict_classes = _predict_classes
