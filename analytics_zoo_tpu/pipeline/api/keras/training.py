"""Training engine — the TPU-native replacement for the reference's
``InternalDistriOptimizer`` (``Topology.scala:1062-1540``) and the
``compile/fit/evaluate/predict`` facade (``Topology.scala:135,343,418,496``).

Architecture (vs the reference's per-iteration Spark jobs + BlockManager
parameter-server allreduce, ``wp-bigdl.md:113-160``):

* ONE jitted ``train_step`` — forward, backward, optimizer update — traced
  once, compiled by XLA, and run per minibatch with donated buffers.
* Data parallelism = batch sharded over the mesh ``data`` axis
  (``NamedSharding``); params replicated. XLA GSPMD inserts the gradient
  psum over ICI — there is no separate communication runtime to operate.
* Input batches stream through ``FeatureSet`` with a background assembly
  thread + double-buffered ``device_put`` so the chip never waits on the host.
* Failure handling keeps the reference's semantics
  (``Topology.scala:1171-1253``): on a step failure, reload the latest
  checkpoint and retry, bounded by ``zoo.failure.retry_times`` within
  ``zoo.failure.retry_window_sec``; checkpoints are cut on the
  ``set_checkpoint`` trigger (``Topology.scala:245-255,1161-1168``).
"""

from __future__ import annotations

import collections
import logging
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ....common import anomaly, faults
from ....common.context import get_zoo_context
from ....common.reliability import RetryBudget
from ....common.triggers import (EveryEpoch, MaxEpoch, SeveralIteration,
                                 TrainLoopState, Trigger)
from ....feature.feature_set import FeatureSet, prefetch_to_device
from ....observability import default_registry, instrument_jit, span
from ....parallel import mesh as mesh_lib
from ....utils.checkpoint import CheckpointManager
from . import metrics as metrics_lib
from . import objectives, optimizers as optim_lib
from .engine import KerasNet

log = logging.getLogger("analytics_zoo_tpu.training")


class TrainingPreempted(SystemExit):
    """Raised out of ``fit`` after a SIGTERM-requested final checkpoint
    (``zoo.checkpoint.on_sigterm``): the snapshot is on disk, in-memory
    model state is published, and the process should now exit — a
    ``SystemExit`` subclass so it escapes the step-failure retry loop and
    terminates cleanly (the TPU-preemption analogue of the reference's
    driver-failure snapshot)."""


class TrainingDiverged(RuntimeError):
    """The anomaly sentinels (``zoo.train.sentinel=recover``) could not
    contain a divergence: either skip-then-rollback recovery exhausted
    its ``zoo.train.max_rollbacks`` budget, or escalation was required
    with no checkpoint to roll back to. Raised INSTEAD of looping
    forever or silently training on garbage — the params published on
    the model are the last known-good (restored) state."""


class _RollbackRequested(RuntimeError):
    """Internal escalation signal: more than
    ``zoo.train.max_skips_per_epoch`` updates were discarded in one
    epoch — reload the last good checkpoint and replay with the
    offending data window skipped. Handled by ``_fit_with_retry``
    under the rollback :class:`RetryBudget`; never escapes ``fit``."""

    def __init__(self, skips: int, epoch: int):
        super().__init__(
            f"{skips} anomalous step(s) skipped in epoch {epoch} "
            f"(zoo.train.max_skips_per_epoch exceeded)")
        self.skips = skips
        self.epoch = epoch


#: shape of the "no fault" train.grads input — a module constant so the
#: hot loop hands the SAME host array to every healthy dispatch instead
#: of allocating one per step
_NO_FAULT = np.zeros(2, np.float32)


class CompiledSpec:
    def __init__(self, optimizer, loss, metrics):
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics


# ---------------------------------------------------------------------------
# data iteration helpers
# ---------------------------------------------------------------------------

def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _num_examples(x) -> int:
    return _as_list(x)[0].shape[0]


def _take(x, idx):
    xs = [np.asarray(a)[idx] for a in _as_list(x)]
    return xs if len(xs) > 1 else xs[0]


def _host_once(x):
    """Materialize device-resident arrays on host ONCE before a batch loop —
    ``_take``'s per-batch ``np.asarray`` would otherwise re-read the whole
    array from HBM every batch (FeatureSet keeps ``jax.Array`` features
    device-resident for the extract→fit chain)."""
    if x is None:
        return None
    xs = [np.asarray(a) if isinstance(a, jax.Array) else a
          for a in _as_list(x)]
    return xs if len(xs) > 1 else xs[0]


def iter_batches(x, y, batch_size: int, *, shuffle: bool, seed: int,
                 drop_last: bool):
    """Host-side minibatch iterator over numpy arrays (evaluate/predict path;
    training streams through ``FeatureSet`` instead)."""
    x, y = _host_once(x), _host_once(y)
    n = _num_examples(x)
    order = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(order)
    end = n - (n % batch_size) if drop_last else n
    for i in range(0, end, batch_size):
        idx = order[i:i + batch_size]
        yield _take(x, idx), (None if y is None else _take(y, idx))


def _pad_to(x, size: int):
    """Pad the batch dim to ``size`` by repeating the last row — ONE policy
    for both host (numpy) and device-resident (jax) arrays, so the
    device-cache fast path pads identically to the host path."""
    xs = _as_list(x)
    out = []
    for a in xs:
        xp = jnp if isinstance(a, jax.Array) else np
        a = a if isinstance(a, jax.Array) else np.asarray(a)
        pad = size - a.shape[0]
        if pad > 0:
            a = xp.concatenate([a, xp.repeat(a[-1:], pad, axis=0)], axis=0)
        out.append(a)
    return out if len(out) > 1 else out[0]


def _round_up(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def _aux_loss_sum(state):
    """Sum of every ``aux_loss`` leaf a layer left in the network state
    (e.g. ``SparseMoE``'s load-balance loss). A trace-time pytree walk —
    models without aux losses pay nothing. Returns None when absent."""
    total = None
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if path and getattr(path[-1], "key", None) == "aux_loss":
            total = leaf if total is None else total + leaf
    return total


def _stack_batches(items):
    """Stack K ``(x, y)`` minibatches into one ``(K, batch, ...)`` chunk for
    the multi-step scan dispatch. ``None`` labels pass through."""
    return jax.tree.map(lambda *xs: np.stack([np.asarray(a) for a in xs], axis=0),
                        *items)


def _chunked(it, k: int):
    buf = []
    for item in it:
        buf.append(item)
        if len(buf) == k:
            yield _stack_batches(buf)
            buf = []
    if buf:
        yield _stack_batches(buf)


class _FullPassEveryEpoch(Trigger):
    """``EveryEpoch`` over a sliced dataset: fires only when the finished
    slice pass completes a FULL pass over all slices
    (``ZooTrigger.scala:53-58``: ``currentSlice % numSlice == 0``)."""

    def __init__(self, num_slices: int):
        self.num_slices = int(num_slices)

    def __call__(self, state: TrainLoopState) -> bool:
        return state.epoch_finished and state.epoch % self.num_slices == 0


def _fired_within(trigger: Optional[Trigger], state: TrainLoopState,
                  prev_iter: int) -> bool:
    """Whether a trigger fired at any step in ``(prev_iter, state.iteration]``.
    With fused dispatches the loop only observes chunk boundaries; interval
    triggers are checked over the whole window so a fire inside the chunk is
    not lost — it is acted on at the boundary, up to (window-1) steps late:
    K-1 for scan chunks, a whole epoch for device_cache (which warns when a
    SeveralIteration interval is finer than the epoch)."""
    if trigger is None:
        return False
    if isinstance(trigger, SeveralIteration):
        return state.iteration // trigger.interval > prev_iter // trigger.interval
    return trigger(state)


def _write_param_histograms(tb, params, epochs, iteration,
                            n_steps: int = 0) -> None:
    """Per-layer weight histograms when the TrainSummary's "Parameters"
    trigger fires for any epoch in ``epochs`` (reference:
    ``TrainSummary.setSummaryTrigger("Parameters", ...)`` +
    ``Summary.scala``'s histogram writer). Called only at boundaries where
    the params are host-visible; under fused-epoch dispatch that is the
    final epoch of a fused block, covering the whole block's epochs —
    ``n_steps`` (steps per epoch) reconstructs each covered epoch's own
    boundary iteration, ending at ``iteration``, and an iteration-based
    trigger is checked over that epoch's whole ``(boundary - n_steps,
    boundary]`` window (``_fired_within`` semantics: a fire landing
    mid-epoch is acted on at the boundary, not dropped)."""
    epochs = list(epochs)
    trig = getattr(tb, "parameters_trigger", None)
    if trig is not None:
        # Trigger-like form: evaluated per covered epoch (params are only
        # host-visible at the block end, but the *decision* must match
        # what per-epoch dispatch would have decided); without n_steps the
        # window degrades to the boundary iteration itself
        last = len(epochs) - 1
        window = max(n_steps, 1)
        if not any(_fired_within(
                trig,
                TrainLoopState(iteration=iteration - (last - k) * n_steps,
                               epoch=e, epoch_finished=True),
                prev_iter=iteration - (last - k) * n_steps - window)
                   for k, e in enumerate(epochs)):
            return
    else:
        freq = getattr(tb, "parameters_every_epochs", None)
        if not freq or not any(e % freq == 0 for e in epochs):
            return
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        tb.add_histogram(f"Parameters/{name}", np.asarray(leaf), iteration)


@jax.jit
def _copy_leaves(leaves):
    return [jnp.copy(a) for a in leaves]


def _clone_tree(tree):
    """Fresh buffers for every array leaf. The donated train step deletes its
    input buffers, so any tree that outlives a step (``model.params``, the
    retry snapshot) must never alias one that enters the step.

    All device leaves are copied in ONE jitted dispatch: a per-leaf
    ``jnp.copy`` costs a separate ``jit(copy)`` trace/dispatch per leaf —
    over a tunneled device link that is ~0.6 s of compile per leaf the
    first time a tree arrives with new shardings, and a device round-trip
    per leaf every time."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    dev_idx = [i for i, a in enumerate(leaves) if isinstance(a, jax.Array)]
    if dev_idx:
        copies = _copy_leaves([leaves[i] for i in dev_idx])
        for i, c in zip(dev_idx, copies):
            leaves[i] = c
    leaves = [np.copy(a) if isinstance(a, np.ndarray) else a for a in leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class _SentinelMonitor:
    """Host-side bookkeeping for the packed per-step sentinel flags
    (``common/anomaly.py``; one int32 per step, ``(K,)`` per scan chunk).

    Flag readbacks trail the dispatch stream by a small lag window so
    observing them never syncs the pipeline the way an eager per-step
    read would — the device-side skip already happened inside the step;
    the host only needs the flags for metrics, the per-epoch skip
    budget, and the rollback replay set, all of which tolerate a
    few-dispatch delay. Everything here is deterministic: the chaos
    harness reconciles the counters exactly against an injected
    ``train.grads`` plan."""

    #: dispatches a flag word may trail the stream before being read
    LAG = 4

    def __init__(self, loop: "TrainingLoop", cfg: anomaly.SentinelConfig):
        self.loop = loop
        self.cfg = cfg
        self.pending: collections.deque = collections.deque()
        self.epoch = 0
        self.epoch_start = 0                # iteration at epoch start
        self.epoch_skips = 0
        self.epoch_flags: List[int] = []    # one per recorded loss
        self.epoch_step_iters: List[int] = []   # global iter per loss

    def begin_epoch(self, epoch: int, start_iter: int) -> None:
        self.drain()                        # belongs to the PREVIOUS epoch
        self.epoch = epoch
        self.epoch_start = start_iter
        self.epoch_skips = 0
        self.epoch_flags = []
        self.epoch_step_iters = []

    def step_key(self, it: int):
        """Replay-stable identity of a dispatched step: (epoch, ordinal
        within the epoch). Global iteration numbers shift when a
        mid-epoch snapshot restores (the epoch re-streams from batch 0
        while the iteration counter resumes mid-epoch), but the batch
        order per epoch is deterministic — the ordinal is what maps
        back to the same data window on replay."""
        return (self.epoch, it - self.epoch_start)

    def push(self, first_iter: int, flags_dev) -> None:
        """Queue one dispatch's flag output (scalar or (K,) vector)."""
        shape = getattr(flags_dev, "shape", ())
        k = int(shape[0]) if shape else 1
        self.epoch_step_iters.extend(range(first_iter, first_iter + k))
        self.pending.append((first_iter, flags_dev))
        if len(self.pending) > self.LAG:
            self._drain_one()

    def note_replay_skip(self, k: int) -> None:
        """``k`` steps of a rollback replay were not re-dispatched (the
        offending data window) — counted as skipped, no loss recorded."""
        self.loop._m_skipped.inc(k)

    def drain(self) -> None:
        while self.pending:
            self._drain_one()

    def _drain_one(self) -> None:
        first_iter, flags_dev = self.pending.popleft()
        words = np.atleast_1d(np.asarray(flags_dev))
        for j, word in enumerate(words):
            f = int(word)
            self.epoch_flags.append(f)
            if f & anomaly.GRAD_CLIPPED:
                self.loop._m_clip.inc()
            kinds = anomaly.kinds_of(f)
            if not kinds:
                continue
            it = first_iter + j
            for kind in kinds:
                self.loop._m_anomaly[kind].inc()
            self.loop._registry.emit(
                "train.anomaly", iteration=it, epoch=self.epoch,
                kinds=",".join(kinds), mode=self.cfg.mode,
                action="skip" if self.cfg.mode == "recover" else "warn")
            if self.cfg.mode == "recover":
                self.loop._m_skipped.inc()
                self.loop._anomalous_steps.add(self.step_key(it))
                self.epoch_skips += 1
                log.warning(
                    "anomalous step at iteration %d (%s): update "
                    "discarded (%d/%d skips this epoch)", it,
                    ",".join(kinds), self.epoch_skips,
                    self.cfg.max_skips_per_epoch)
            else:
                log.warning(
                    "anomalous step at iteration %d (%s) — "
                    "zoo.train.sentinel=warn: update APPLIED", it,
                    ",".join(kinds))
        if (self.cfg.mode == "recover"
                and self.epoch_skips > self.cfg.max_skips_per_epoch):
            raise _RollbackRequested(self.epoch_skips, self.epoch)

    def loss_mask(self, n: int) -> np.ndarray:
        """Valid-loss mask over this epoch's ``n`` recorded losses: in
        recover mode an anomalous step's loss was never applied, so it
        is excluded from the epoch mean (matching a run that never saw
        the poison batch)."""
        self.drain()
        mask = np.ones(n, bool)
        if self.cfg.mode == "recover":
            for i, f in enumerate(self.epoch_flags[:n]):
                if f & anomaly.ANOMALY_MASK:
                    mask[i] = False
        return mask


# ---------------------------------------------------------------------------
# The training loop (InternalDistriOptimizer / LocalOptimizer unified)
# ---------------------------------------------------------------------------

class TrainingLoop:
    """Owns the jitted step functions for one (model, optimizer, loss) triple."""

    def __init__(self, model: KerasNet, optimizer: optax.GradientTransformation,
                 loss: Callable, metrics: Sequence[metrics_lib.Metric] = ()):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = list(metrics)
        self.mesh = mesh_lib.global_mesh()
        self._train_step = None
        self._scan_step = None
        self._epoch_fns: Dict[Tuple, Any] = {}
        self._eval_step = None
        self._predict_step = None
        # device-resident copy of the latest FeatureSet (device_cache path)
        # — re-uploading per fit call costs a full host→device transfer of
        # the whole set. The entry HOLDS the fs object: a bare id() key
        # could be reused by a new FeatureSet after GC and silently serve
        # the old dataset's arrays.
        self._data_cache: Dict[Tuple, Any] = {}
        # observability (docs/guides/OBSERVABILITY.md): every fit updates
        # the zoo_train_* family in the process-wide registry
        self._registry = default_registry()
        self._m_step_time = self._registry.histogram(
            "zoo_train_step_seconds",
            "optimizer-step wall time (amortized over fused dispatches)")
        self._m_throughput = self._registry.gauge(
            "zoo_train_records_per_sec", "training examples/sec, last epoch")
        self._m_mfu = self._registry.gauge(
            "zoo_train_mfu",
            "achieved model-FLOPs utilization, last epoch "
            "(zoo.metrics.flops + a known chip peak)")
        self._m_steps = self._registry.counter(
            "zoo_train_steps_total", "optimizer steps run")
        self._m_examples = self._registry.counter(
            "zoo_train_examples_total", "training examples consumed")
        # evaluate/predict get the same treatment fit got (ROADMAP
        # eval/predict instrumentation pass): weighted step-time
        # histograms + record counters, spans around the whole pass
        self._m_eval_step_time = self._registry.histogram(
            "zoo_eval_step_seconds",
            "evaluate step wall time (amortized over the streamed batches)")
        self._m_eval_records = self._registry.counter(
            "zoo_eval_examples_total", "examples evaluated (pad rows excluded)")
        self._m_predict_step_time = self._registry.histogram(
            "zoo_predict_step_seconds",
            "predict step wall time (amortized over the streamed batches)")
        self._m_predict_records = self._registry.counter(
            "zoo_predict_examples_total", "examples predicted")
        self._flops_per_example: Optional[float] = None
        # durable checkpointing (docs/guides/TRAINING.md): the manager of
        # the fit attempt in flight (its async writer is joined/closed by
        # _close_active_ckpt_mgr) and the SIGTERM preemption latch
        self._active_ckpt_mgr: Optional[CheckpointManager] = None
        self._preempted = threading.Event()
        # SIGTERM grace budget (zoo.checkpoint.sigterm_grace_s): the
        # in-flight dispatch segment's start stamp + EWMA duration
        # estimate, and — only when the estimate already exceeds the
        # budget — a cloned copy of the last boundary state the handler
        # can cut a MID-EPOCH snapshot from (the in-flight trees are
        # donated to the dispatch and unreadable by then)
        self._sigterm_grace: Optional[float] = None
        self._segment_t0: Optional[float] = None
        self._segment_est: Optional[float] = None
        self._segment_count = 0     # loop-lifetime; first sample discarded
        self._boundary_ref = None
        self._apply_loss = None     # resolved once per loop (fused CE)
        # anomaly sentinels (docs/guides/TRAINING.md "Anomaly detection
        # & recovery"): config resolved once per loop like _apply_loss;
        # the per-fit recovery state (flagged iterations, rollback
        # budget) is (re)initialized at each fit() entry
        self._sentinel: Optional[anomaly.SentinelConfig] = None
        # kind iterates anomaly.KIND_BITS — a 3-entry module constant
        # (nan_loss/nan_grad/spike), bounded just like a literal
        self._m_anomaly = {
            kind: self._registry.counter(  # zoolint: disable=ZL015 bounded label set
                "zoo_train_anomaly_total",
                "anomalous training steps detected by the sentinels, by "
                "kind (zoo.train.sentinel)", labels={"kind": kind})
            for _bit, kind in anomaly.KIND_BITS}
        self._m_skipped = self._registry.counter(
            "zoo_train_skipped_steps_total",
            "optimizer steps whose update was discarded (sentinel skip) "
            "or not re-dispatched on rollback replay")
        self._m_rollback = self._registry.counter(
            "zoo_train_rollback_total",
            "skip-budget escalations that reloaded the last good "
            "checkpoint and replayed past the offending window")
        self._m_clip = self._registry.counter(
            "zoo_train_grad_clip_engaged_total",
            "steps where zoo.train.grad_clip global-norm clipping "
            "actually rescaled the gradients")
        self._anomalous_steps: set = set()   # {(epoch, ordinal)} flagged
        self._rollback_budget: Optional[RetryBudget] = None
        self._rollback_pending = False
        # goodput/badput attribution (docs/guides/OBSERVABILITY.md
        # "Goodput & performance attribution"): one ledger per fit,
        # created at fit_feature_set entry when zoo.goodput.enabled
        self._goodput = None
        self._gp_restarting = False   # a retry attempt's resume pending

    # -- goodput attribution -------------------------------------------------
    def _gp_note(self, category: str) -> None:
        """Attribute wall clock since the ledger's mark to ``category``
        (no-op outside an accounted fit)."""
        if self._goodput is not None:
            self._goodput.note(category)

    # -- jitted steps -------------------------------------------------------
    #: the labels of the most recent fused-CE gauge write in this process —
    #: a later non-fused (or differently-headed) loop zeroes the stale
    #: series so the scrape never claims fusion is active when it is not
    _last_fused_labels = None
    _FUSED_GAUGE_HELP = ("1 while the fused blockwise LM-head cross-entropy "
                         "is active for the current training loop")

    def _loss_application(self):
        """``fn(params, net_state, x, y, rng) -> (loss, new_state)`` — the
        forward+loss shared by every training-step builder. Resolves the
        fused LM-head cross-entropy (``fused_loss.resolve_fused_loss``,
        ``zoo.train.fused_ce``) ONCE per loop — the scan/epoch builders
        call this at trace time, and re-resolving would re-log and
        re-write the gauge on every retrace: a big-vocab Dense head with
        a sparse-CE loss streams through ``ops/fused_cross_entropy`` so the
        ``(B·T, V)`` logits tensor never materializes; everything else runs
        the plain apply + objective (the oracle path, which ``evaluate``
        always uses)."""
        if self._apply_loss is not None:
            return self._apply_loss
        model, loss_fn = self.model, self.loss
        from .fused_loss import resolve_fused_loss
        from .seq_pipe import (pipe_intercept, resolve_pipe_spec,
                               resolve_seq_attention, seq_attention_scope)
        from .sharded_embed import resolve_sharded_embeddings
        # sequence/pipeline step integration (zoo.train.seq_attention /
        # zoo.train.pipe_stages): resolved once per loop like the fused
        # loss, applied as trace-time scopes around every builder's
        # forward so existing models ride seq/pipe meshes unchanged
        seq_mode = resolve_seq_attention()
        pipe_spec = resolve_pipe_spec(model)
        # row-sharded embedding engine (zoo.embed.sharded): resolved once
        # per loop too — it flips engaged layers' param spec to row
        # partitioning, which must happen before fit resolves shardings
        embed_hook = resolve_sharded_embeddings(model)

        def embed_scope():
            # intercept_layer_calls(None) would DISABLE outer scopes for
            # the duration — only open a scope when a hook resolved
            import contextlib

            from .engine import intercept_layer_calls
            if embed_hook is None:
                return contextlib.nullcontext()
            return intercept_layer_calls(embed_hook)
        spec = resolve_fused_loss(model, loss_fn)
        prev = TrainingLoop._last_fused_labels
        if spec is None:
            if prev is not None:
                # prev = the head=/vocab= dict of the LAST engaged
                # loop (zeroing the stale series); bounded by the
                # model architectures built in-process
                self._registry.gauge("zoo_train_fused_ce",  # zoolint: disable=ZL015 bounded label set
                                     self._FUSED_GAUGE_HELP,
                                     labels=prev).set(0)
                TrainingLoop._last_fused_labels = None

            def apply_loss(p, net_state, x, y, rng):
                with seq_attention_scope(seq_mode), \
                        pipe_intercept(pipe_spec, p, training=True), \
                        embed_scope():
                    yp, ns = model.apply(p, net_state, x, training=True,
                                         rng=rng)
                return loss_fn(y, yp), ns
            self._apply_loss = apply_loss
            return apply_loss
        log.info("fused LM-head cross-entropy engaged: head=%s vocab=%d%s "
                 "(zoo.train.fused_ce; the (N, V) logits tensor is never "
                 "materialized)", spec.head.name, spec.head.output_dim,
                 " VOCAB-SHARDED over the model axis" if spec.sharded
                 else "")
        labels = {"head": spec.head.name,
                  "vocab": str(spec.head.output_dim),
                  "sharded": "1" if spec.sharded else "0"}
        if prev is not None and prev != labels:
            # stale-series zeroing, same bounded head=/vocab= set
            self._registry.gauge("zoo_train_fused_ce",  # zoolint: disable=ZL015 bounded label set
                                 self._FUSED_GAUGE_HELP, labels=prev).set(0)
        # head/vocab identify the fused head (catalog row documents
        # the keys); bounded by the model architectures in-process
        self._registry.gauge("zoo_train_fused_ce", self._FUSED_GAUGE_HELP,  # zoolint: disable=ZL015 bounded label set
                             labels=labels).set(1)
        TrainingLoop._last_fused_labels = labels

        def apply_loss(p, net_state, x, y, rng):
            # scopes chain: the fused head's own intercept (opened inside
            # apply_and_loss) composes with the embedding hook
            with seq_attention_scope(seq_mode), \
                    pipe_intercept(pipe_spec, p, training=True), \
                    embed_scope():
                return spec.apply_and_loss(model, p, net_state, x, y,
                                           rng=rng)
        self._apply_loss = apply_loss
        return apply_loss

    def _remat_wrapper(self):
        """``zoo.train.remat`` (opt-in): wrap the per-step forward+loss in
        ``jax.checkpoint`` so the backward recomputes activations instead of
        saving them across the scan — 32k training can raise batch/K
        instead of sitting at batch 1. ``true``/``dots`` keeps MXU outputs
        (``dots_with_no_batch_dims_saveable`` — recompute the cheap
        elementwise chains, keep the matmuls); ``full`` saves nothing
        (maximum memory relief, a full extra forward of recompute). See
        TRAINING.md "Long-context tuning" for the trade-off table."""
        from ....common.context import (FALSE_FLAG_SPELLINGS,
                                        TRUE_FLAG_SPELLINGS)
        mode = get_zoo_context().get("zoo.train.remat", False)
        if isinstance(mode, str):
            low = mode.strip().lower()
            if low in FALSE_FLAG_SPELLINGS or low == "none":
                return lambda f: f
            if low in TRUE_FLAG_SPELLINGS or low in (
                    "dots", "dots_with_no_batch_dims_saveable"):
                policy = jax.checkpoint_policies.\
                    dots_with_no_batch_dims_saveable
            elif low in ("full", "all", "nothing_saveable"):
                policy = jax.checkpoint_policies.nothing_saveable
            else:
                raise ValueError(f"zoo.train.remat must be "
                                 f"false|true|dots|full, got {mode!r}")
        elif mode:
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        else:
            return lambda f: f
        return lambda f: jax.checkpoint(f, policy=policy)

    def _sentinel_config(self) -> anomaly.SentinelConfig:
        """Resolve the anomaly-sentinel/grad-clip knobs ONCE per loop
        (like the fused-loss resolution): every step builder of a loop
        must agree on the step signature, and with ``sentinel=off`` and
        no clipping the builders emit the historical step exactly —
        zero sentinel ops, bit-identical numerics."""
        if self._sentinel is None:
            self._sentinel = anomaly.resolve_config()
            cfg = self._sentinel
            if cfg.sentinel:
                log.info(
                    "anomaly sentinels armed (zoo.train.sentinel=%s): "
                    "nan-loss/nan-grad checks + grad-norm spike at %gx "
                    "EWMA%s%s", cfg.mode, cfg.spike_factor,
                    "; updates from anomalous steps are DISCARDED, "
                    "escalating to checkpoint rollback past "
                    f"{cfg.max_skips_per_epoch} skips/epoch"
                    if cfg.mode == "recover" else "",
                    "; train.grads fault injection compiled in"
                    if cfg.faults else "")
        return self._sentinel

    def _make_step_core(self):
        """The per-step forward/backward/update shared by the single-step
        and scan builders. Returns ``(core_fn, cfg)``.

        With the sentinel layer inactive (``zoo.train.sentinel=off`` and
        no ``zoo.train.grad_clip``) the core is EXACTLY the historical
        step — no extra inputs, outputs, or ops, so the off mode
        preserves step numerics bit-for-bit. Active, the core grows a
        sentinel-state carry and a packed int32 flag output
        (``common/anomaly.py``): non-finite loss, non-finite/spiking
        global grad norm, clip engagement — computed on device inside
        the same fused program, no extra host sync. In ``recover`` mode
        an anomalous step's params/opt-state/net-state updates are
        discarded on device (the carry keeps the pre-step values); the
        host observes the flag later and handles budget escalation."""
        opt = self.optimizer
        apply_loss = self._loss_application()
        remat = self._remat_wrapper()
        cfg = self._sentinel_config()

        def backward(params, net_state, x, y, rng):
            def lfn(p):
                l, ns = apply_loss(p, net_state, x, y, rng)
                aux = _aux_loss_sum(ns)
                return (l if aux is None else l + aux), ns
            return jax.value_and_grad(remat(lfn), has_aux=True)(params)

        if not cfg.active:
            def plain(params, opt_state, net_state, rng, x, y):
                (l, ns), grads = backward(params, net_state, x, y, rng)
                updates, opt_state = opt.update(grads, opt_state, params)
                opt_state = self._pin_opt_state(opt_state)
                params = optax.apply_updates(params, updates)
                return params, opt_state, ns, l
            return plain, cfg

        def guarded(params, opt_state, net_state, sstate, rng, fault, x, y):
            (l, ns), grads = backward(params, net_state, x, y, rng)
            if cfg.faults:
                # chaos only (zoo.faults.enabled at build time): apply
                # the host-scheduled train.grads poison code on device
                l, grads = anomaly.inject_grads(l, grads, fault[0],
                                                fault[1])
            gnorm = anomaly.global_norm(grads)
            if cfg.sentinel:
                flags, sstate = anomaly.check(l, gnorm, sstate,
                                              cfg.spike_factor)
            else:
                flags = jnp.zeros((), jnp.int32)
            if cfg.grad_clip > 0:
                grads, engaged = anomaly.clip_by_global_norm(
                    grads, gnorm, cfg.grad_clip)
                flags = flags | jnp.where(engaged, anomaly.GRAD_CLIPPED,
                                          0).astype(jnp.int32)
            if cfg.mode == "recover":
                # skip-batch: an anomalous step's update is not applied —
                # params/opt-state/net-state keep their pre-step values
                # (the optimizer count does not advance either, so the
                # surviving trajectory matches a run that never saw the
                # poison batch). lax.cond, not a where-select: the
                # healthy path must run EXACTLY the plain update — a
                # per-leaf select costs extra full passes over params +
                # moments every step (measured ~30% on the NCF bench
                # shape), while the untaken skip branch costs nothing
                bad = (flags & anomaly.ANOMALY_MASK) > 0

                def _apply(operand):
                    p, o, g, new_ns = operand
                    updates, new_opt = opt.update(g, o, p)
                    new_opt = self._pin_opt_state(new_opt)
                    return (optax.apply_updates(p, updates), new_opt,
                            new_ns)

                def _skip(operand):
                    p, o, _g, _new_ns = operand
                    return p, o, net_state

                params, opt_state, net_state = jax.lax.cond(
                    bad, _skip, _apply, (params, opt_state, grads, ns))
            else:
                updates, opt_state = opt.update(grads, opt_state, params)
                opt_state = self._pin_opt_state(opt_state)
                params = optax.apply_updates(params, updates)
                net_state = ns
            return params, opt_state, net_state, sstate, l, flags

        return guarded, cfg

    def build_train_step(self):
        core, cfg = self._make_step_core()
        # instrument_jit == jax.jit + compile accounting: every first
        # compile lands in zoo_jit_compile_*, every recompile under a new
        # batch shape emits a jit.retrace event naming the path
        self._train_step = instrument_jit(core, name="train.step",
                                          registry=self._registry,
                                          donate_argnums=(0, 1, 2))
        return self._train_step

    def _make_scan_body(self, base_rng):
        """The shared per-step scan body (fold_in rng schedule → grad →
        optimizer update) used by both the K-step chunk dispatch and the
        whole-epoch dispatch, so the two fused paths can never diverge
        numerically from each other or from the single-step path."""
        core, cfg = self._make_step_core()

        if not cfg.active:
            def body(carry, batch):
                params, opt_state, net_state, i = carry
                x, y = batch
                rng = jax.random.fold_in(base_rng, i)
                params, opt_state, ns, l = core(params, opt_state,
                                                net_state, rng, x, y)
                return (params, opt_state, ns, i + 1), l
            return body

        def body(carry, batch):
            params, opt_state, net_state, sstate, i = carry
            x, y, fault = batch
            rng = jax.random.fold_in(base_rng, i)
            params, opt_state, ns, sstate, l, flags = core(
                params, opt_state, net_state, sstate, rng, fault, x, y)
            return (params, opt_state, ns, sstate, i + 1), (l, flags)
        return body

    def build_scan_step(self):
        """Multi-step train function: runs K optimizer steps per dispatch via
        ``lax.scan`` over stacked batches of shape ``(K, batch, ...)``.

        This is the TPU-idiomatic answer to the reference's
        one-Spark-job-per-iteration scheduling overhead
        (``wp-bigdl.md:171-173``: >10% of compute lost to task dispatch at
        scale): here the per-step Python/runtime dispatch cost is amortized
        K-fold, leaving XLA a single fused program per chunk. With the
        sentinel layer active the chunk additionally carries the EWMA
        state and returns a ``(K,)`` packed flag vector alongside the
        ``(K,)`` losses — one readback, per-step granularity."""
        cfg = self._sentinel_config()

        if not cfg.active:
            def chunk(params, opt_state, net_state, base_rng, iter0, xs, ys):
                (params, opt_state, net_state, _), losses = jax.lax.scan(
                    self._make_scan_body(base_rng),
                    (params, opt_state, net_state, iter0), (xs, ys))
                return params, opt_state, net_state, losses
        else:
            def chunk(params, opt_state, net_state, sstate, base_rng,
                      iter0, xs, ys, fault):
                (params, opt_state, net_state, sstate, _), \
                    (losses, flags) = jax.lax.scan(
                        self._make_scan_body(base_rng),
                        (params, opt_state, net_state, sstate, iter0),
                        (xs, ys, fault))
                return params, opt_state, net_state, sstate, losses, flags

        self._scan_step = instrument_jit(chunk, name="train.scan_chunk",
                                         registry=self._registry,
                                         donate_argnums=(0, 1, 2))
        return self._scan_step

    def _shard_opt_state(self, opt_state, psh, repl):
        """Committed placement for optimizer state: param-shaped leaves
        (adam moments) follow the param shardings, counters and the like
        replicate. Used for BOTH fresh and reused state so every fit call
        presents identical input shardings to the jitted step — otherwise
        the first call hands uncommitted counters while later calls hand
        committed ones, and each fit() misses the jit cache and recompiles
        the whole epoch program (~20 s on a real chip).

        ``zoo.train.zero_sharding``: ZeRO-1 — moments additionally shard
        over the ``data`` axis (``mesh_lib.zero_sharding_for``); the jitted
        step re-pins the updated state each step so GSPMD keeps the
        reduce-scatter/all-gather form instead of drifting back to
        replicated."""
        zero = bool(get_zoo_context().get("zoo.train.zero_sharding", False))

        def moment_sharding(leaf, base):
            if not zero:
                return base
            return mesh_lib.zero_sharding_for(base, np.shape(leaf),
                                              self.mesh)

        try:
            shardings = optax.tree_map_params(
                self.optimizer, lambda s, sh: moment_sharding(s, sh),
                opt_state, psh,
                transform_non_params=lambda s: repl)
            # the sharding TREE (matching opt_state's structure) doubles as
            # the per-step constraint target under zero_sharding
            self._opt_state_shardings = shardings if zero else None
            return jax.tree.map(lambda s, sh: jax.device_put(s, sh),
                                opt_state, shardings)
        except (ValueError, TypeError, AttributeError) as e:
            # structure quirks of custom/wrapped optimizers (e.g.
            # multi_transform label fns failing placeholder introspection):
            # replicated moments are correct — and identical under pure DP —
            # but under TP they reshard every step, so say so
            log.warning("could not apply param shardings to the optimizer "
                        "state (%s); moments stay replicated", e)
            self._opt_state_shardings = None
            return jax.device_put(opt_state, repl)

    def _pin_opt_state(self, opt_state):
        """In-step sharding constraint keeping ZeRO-sharded moments sharded
        across scan iterations (no-op when zero_sharding is off)."""
        sh = getattr(self, "_opt_state_shardings", None)
        if sh is None:
            return opt_state
        return jax.tree.map(jax.lax.with_sharding_constraint, opt_state, sh)

    def build_epoch_fn(self, n: int, batch_size: int, n_steps: int,
                       shuffle: bool = True):
        """Whole-epoch train function over a device-resident dataset
        (``zoo.train.device_cache``): shuffle (jax.random.permutation) and all
        ``n_steps`` optimizer steps run in ONE dispatch, so per-step host and
        dispatch latency vanish entirely.

        This is the HBM analogue of ``CachedDistributedFeatureSet``
        (``FeatureSet.scala:222-322``): the reference caches the dataset in
        executor RAM and re-shuffles an index per epoch; here the cache lives
        in device HBM and the re-shuffle is an on-device gather. The epoch's
        shuffled view is re-laid-out once per epoch under the stacked batch
        sharding, so the per-step scan body stays identical to the chunked
        path (numerically the same rng schedule as well)."""
        if self._sentinel_config().active:
            raise RuntimeError(
                "whole-epoch dispatch is unavailable with the anomaly-"
                "sentinel/grad-clip layer active (zoo.train.sentinel / "
                "zoo.train.grad_clip) — fit falls back to the streamed "
                "path automatically")
        key = (n, batch_size, n_steps, shuffle)
        if key in self._epoch_fns:
            return self._epoch_fns[key]
        body = self._make_epoch_body(n, batch_size, n_steps, shuffle)

        def epoch(params, opt_state, net_state, base_rng, iter0, shuffle_rng,
                  xs, ys):
            (params, opt_state, net_state, _), losses = body(
                (params, opt_state, net_state, iter0), base_rng, shuffle_rng,
                xs, ys)
            return params, opt_state, net_state, losses

        fn = instrument_jit(epoch, name="train.epoch",
                            registry=self._registry,
                            donate_argnums=(0, 1, 2))
        self._epoch_fns[key] = fn
        return fn

    def _make_epoch_body(self, n, batch_size, n_steps, shuffle):
        """The shared whole-epoch body (on-device shuffle gather → scan of
        optimizer steps) behind BOTH the per-epoch and the fused-epoch
        dispatch, so the two paths cannot diverge numerically."""
        stacked = mesh_lib.stacked_batch_sharding(self.mesh)
        n_used = n_steps * batch_size

        def body(carry, base_rng, shuffle_rng, xs, ys):
            params, opt_state, net_state, it = carry
            if shuffle:
                perm = jax.random.permutation(shuffle_rng, n)[:n_used]
            else:
                perm = jnp.arange(n_used)

            def shuffled(a):
                out = jnp.take(a, perm, axis=0).reshape(
                    (n_steps, batch_size) + a.shape[1:])
                return jax.lax.with_sharding_constraint(out, stacked)

            return jax.lax.scan(
                self._make_scan_body(base_rng),
                (params, opt_state, net_state, it),
                (jax.tree.map(shuffled, xs), jax.tree.map(shuffled, ys)))

        return body

    def build_multi_epoch_fn(self, n: int, batch_size: int, n_steps: int,
                             shuffle: bool, n_epochs: int):
        """``zoo.train.fuse_epochs``: K whole epochs (shuffle + steps) in ONE
        dispatch — a ``lax.scan`` over per-epoch shuffle keys around the
        epoch body. On a tunneled/remote device the per-epoch dispatch +
        loss-readback round-trips are the remaining host cost after
        ``device_cache``; this amortizes them K-fold. The rng schedule is
        identical to the per-epoch path, so losses match bit-for-bit."""
        if self._sentinel_config().active:
            raise RuntimeError(
                "fused-epoch dispatch is unavailable with the anomaly-"
                "sentinel/grad-clip layer active (zoo.train.sentinel / "
                "zoo.train.grad_clip)")
        key = (n, batch_size, n_steps, shuffle, n_epochs)
        if key in self._epoch_fns:
            return self._epoch_fns[key]
        body = self._make_epoch_body(n, batch_size, n_steps, shuffle)

        def multi(params, opt_state, net_state, base_rng, iter0,
                  shuffle_rngs, xs, ys):
            def one_epoch(carry, ep_rng):
                return body(carry, base_rng, ep_rng, xs, ys)

            (params, opt_state, net_state, _), L = jax.lax.scan(
                one_epoch, (params, opt_state, net_state, iter0),
                shuffle_rngs)
            return params, opt_state, net_state, L  # (n_epochs, n_steps)

        fn = instrument_jit(multi, name="train.multi_epoch",
                            registry=self._registry,
                            donate_argnums=(0, 1, 2))
        self._epoch_fns[key] = fn
        return fn

    def build_eval_step(self):
        model, loss_fn, metrics = self.model, self.loss, self.metrics
        pe_loss = objectives.per_example_loss(loss_fn)

        def _update(m):
            """User Metric classes may predate the mask argument; detect the
            two-arg signature once (outside jit) and shim it."""
            try:
                import inspect
                n = len(inspect.signature(m.update).parameters)
            except (TypeError, ValueError):
                n = 3
            if n >= 3:
                return m.update
            return lambda y, yp, mask: m.update(y, yp)

        updates = [(m.name, _update(m)) for m in metrics]

        def step(params, net_state, x, y, mask):
            yp, _ = model.apply(params, net_state, x, training=False, rng=None)
            stats = {name: upd(y, yp, mask) for name, upd in updates}
            if pe_loss is not None:
                stats["loss"] = {"sum": jnp.sum(pe_loss(y, yp) * mask),
                                 "count": jnp.sum(mask)}
            else:
                # cross-batch losses (rank_hinge, custom callables) have no
                # per-example form; the whole-batch loss (which unavoidably
                # includes repeated-pad rows — for rank_hinge an odd real tail
                # also misaligns the assumed (pos, neg) pairing of pad rows)
                # is weighted by the real-row count so pads don't inflate it.
                stats["loss"] = {"sum": loss_fn(y, yp) * jnp.sum(mask),
                                 "count": jnp.sum(mask)}
            return stats

        self._eval_step = instrument_jit(step, name="train.eval_step",
                                         registry=self._registry)
        return self._eval_step

    def build_predict_step(self):
        model = self.model
        # multi-host: batch-sharded outputs span processes, which the host
        # cannot device_get; replicate them on-device (an all-gather over
        # ICI/DCN — the reference ships predictions back through Spark the
        # same way, Predictor.scala:136-208)
        gather = jax.process_count() > 1
        repl = (mesh_lib.replicated_sharding(self.mesh) if gather else None)

        def step(params, net_state, x):
            yp, _ = model.apply(params, net_state, x, training=False, rng=None)
            if gather:
                yp = jax.tree.map(
                    lambda a: jax.lax.with_sharding_constraint(a, repl), yp)
            return yp

        self._predict_step = instrument_jit(step, name="train.predict_step",
                                            registry=self._registry)
        return self._predict_step

    # -- observability ------------------------------------------------------
    def _maybe_compute_flops(self, fn, args, examples_per_dispatch) -> float:
        """One-shot XLA cost-analysis pass caching FLOPs/example for the MFU
        gauge. Opt-in (``zoo.metrics.flops``): the extra ``lower().compile()``
        costs a compile, wasted on backends with no known peak — and
        ``lower`` only reads avals/shardings, so calling it on buffers the
        subsequent dispatch donates is safe. Returns the seconds spent so
        callers can exclude the compile from their epoch-timing window
        (the metrics this pass feeds must not be skewed by it)."""
        if self._flops_per_example is not None:
            return 0.0
        if not get_zoo_context().get("zoo.metrics.flops", False):
            # do NOT latch the off state: the flag is re-read per dispatch
            # (one dict lookup) so enabling it before a later fit on the
            # same compiled model still produces an MFU reading
            return 0.0
        from ....utils import profiling
        self._gp_note("device_step")    # close the step interval first
        t = time.perf_counter()
        try:
            flops = profiling.compiled_flops(fn.lower(*args).compile())
        except Exception:   # backend-dependent; never fail a fit for MFU
            flops = None
        # 0.0 latches "tried and unavailable" so the compile isn't retried
        self._flops_per_example = (
            flops / examples_per_dispatch if flops else 0.0)
        self._gp_note("compile")
        return time.perf_counter() - t

    def _observe_fit_metrics(self, steps: int, dt: float,
                             n_examples: int) -> None:
        """Per-epoch registry update: weighted step-time histogram,
        records/sec gauge, cumulative counters, and — when FLOPs/example
        is known and the chip peak is published — achieved MFU via
        ``utils/profiling.py``."""
        if steps <= 0 or dt <= 0:
            return
        self._m_step_time.observe(dt / steps, n=steps)
        thr = n_examples / dt
        self._m_throughput.set(thr)
        self._m_steps.inc(steps)
        self._m_examples.inc(n_examples)
        if self._flops_per_example:
            from ....utils import profiling
            m = profiling.mfu(self._flops_per_example * thr)
            if m is not None:
                self._m_mfu.set(m)

    # -- checkpoint plumbing ------------------------------------------------
    def _ckpt_manager(self) -> Optional[CheckpointManager]:
        spec = getattr(self.model, "_checkpoint", None)
        if spec is None:
            return None
        ctx = get_zoo_context()
        keep = spec.get("keep")
        if keep is None:  # keep=0 means keep-all, so no falsy check
            keep = int(ctx.get("zoo.checkpoint.keep", 3))
        return CheckpointManager(spec["path"], keep=keep,
                                 registry=self._registry,
                                 ledger=self._goodput)

    def _ckpt_trigger(self) -> Trigger:
        spec = getattr(self.model, "_checkpoint", None) or {}
        return spec.get("trigger") or EveryEpoch()

    def _save_checkpoint(self, mgr: CheckpointManager, loop_state, params,
                         opt_state, net_state, sync: bool = False) -> None:
        """Cut a snapshot. Async by default: the step path pays one host
        transfer and the serialization/commit rides the manager's writer
        thread; ``sync=True`` (the SIGTERM path) blocks until committed."""
        mgr.save(loop_state.iteration,
                 {"params": params, "opt_state": opt_state,
                  "net_state": net_state},
                 meta={"epoch": loop_state.epoch,
                       "iteration": loop_state.iteration,
                       "epoch_finished": loop_state.epoch_finished},
                 sync=sync, mesh=mesh_lib.mesh_metadata(self.mesh))

    def _close_active_ckpt_mgr(self, surface: bool) -> None:
        """Join the active manager's in-flight save. ``surface=True``
        re-raises a background save failure (the end-of-fit surfacing
        point); ``surface=False`` is exception-path cleanup — the failure
        was already counted, and masking the in-flight exception with a
        second one would hide the real crash."""
        mgr, self._active_ckpt_mgr = self._active_ckpt_mgr, None
        if mgr is not None:
            mgr.close(raise_pending=surface)

    def _maybe_preempt(self, mgr, loop_state, params, opt_state,
                       net_state) -> None:
        """SIGTERM arrived (``zoo.checkpoint.on_sigterm``): cut one final
        SYNCHRONOUS checkpoint at this step boundary, publish in-memory
        state, and exit cleanly via :class:`TrainingPreempted`."""
        if mgr is None or not self._preempted.is_set():
            return
        log.warning("SIGTERM: cutting a final synchronous checkpoint at "
                    "iteration %d before exiting", loop_state.iteration)
        try:
            self._save_checkpoint(mgr, loop_state, params, opt_state,
                                  net_state, sync=True)
        except Exception:
            # the process is going down either way; the newest previous
            # snapshot (already committed) remains the resume point
            log.exception("final preemption checkpoint failed")
        model = self.model
        model.params, model.net_state, model.opt_state = _clone_tree(
            (params, net_state, opt_state))
        model.finished_iterations = loop_state.iteration
        raise TrainingPreempted(
            f"training preempted by SIGTERM; final checkpoint cut at "
            f"iteration {loop_state.iteration}")

    def _on_sigterm(self, signum, frame) -> None:
        grace = self._sigterm_grace
        if grace is not None:
            self._try_grace_cut(grace)      # raises when it cuts
        log.warning("SIGTERM received; requesting a final checkpoint at "
                    "the next step boundary")
        self._preempted.set()

    # -- SIGTERM grace budget (zoo.checkpoint.sigterm_grace_s) --------------
    def _segment_begin(self, mgr, loop_state, params, opt_state,
                       net_state) -> None:
        """A dispatch segment (one step / scan chunk / fused epoch) is
        about to enter the device. When the running duration estimate
        already exceeds the grace budget, clone the boundary state NOW —
        the dispatch donates these trees, so by the time the handler
        fires mid-segment the originals are deleted device buffers. A
        segment estimated to finish within the budget skips the clone
        (the handler just waits for the boundary), so the copy is only
        paid in the slow-segment regime it exists for."""
        if self._sigterm_grace is None or mgr is None:
            return
        est = self._segment_est
        if est is not None and est > self._sigterm_grace:
            self._boundary_ref = (
                mgr, loop_state.iteration, loop_state.epoch,
                loop_state.epoch_finished,
                _clone_tree((params, opt_state, net_state)))
        else:
            self._boundary_ref = None
        self._segment_t0 = time.monotonic()

    def _segment_end(self) -> None:
        """Fold the completed segment's wall time into the EWMA estimate
        the handler projects the next boundary from. The loop's FIRST
        segment ever is discarded: it carries the one-time jit compile
        (tens of seconds), and folding it in would overestimate the next
        boundaries — paying boundary clones and cutting mid-epoch
        snapshots when the real boundary is milliseconds away (the
        training-side analogue of serving's ``_DOOMED_MIN_OBS``
        warm-up)."""
        if self._sigterm_grace is None:
            return
        t0 = self._segment_t0
        self._segment_t0 = None
        self._boundary_ref = None
        if t0 is None:
            return
        self._segment_count += 1
        if self._segment_count == 1:
            return                      # compile-contaminated sample
        dur = time.monotonic() - t0
        est = self._segment_est
        self._segment_est = dur if est is None else 0.5 * est + 0.5 * dur

    def _try_grace_cut(self, grace: float) -> None:
        """SIGTERM-handler path: when the estimated time to the next
        step boundary exceeds the grace budget, cut one synchronous
        snapshot of the LAST boundary's state immediately — mid-epoch —
        and exit via :class:`TrainingPreempted`, instead of gambling
        that the in-flight dispatch beats the preemption deadline. No
        estimate, no captured boundary, or a near boundary → return and
        let the normal next-boundary path run."""
        t0, est, ref = self._segment_t0, self._segment_est, \
            self._boundary_ref
        if t0 is None or est is None or ref is None:
            return
        eta = est - (time.monotonic() - t0)
        if eta <= grace:
            return
        # de-arm BEFORE the (multi-second) synchronous save: a supervisor
        # that re-sends SIGTERM while it runs re-enters this handler, and
        # a nested save of the same snapshot interleaved with the paused
        # outer one would corrupt exactly the checkpoint being cut — the
        # re-entrant call must fall through to the boundary-latch path
        self._boundary_ref = None
        self._segment_t0 = None
        mgr, iteration, epoch, epoch_finished, trees = ref
        params, opt_state, net_state = trees
        log.warning("SIGTERM: estimated %.2fs to the next step boundary "
                    "exceeds the %.2fs grace budget; cutting a mid-epoch "
                    "snapshot at iteration %d now", eta, grace, iteration)
        try:
            mgr.save(iteration,
                     {"params": params, "opt_state": opt_state,
                      "net_state": net_state},
                     meta={"epoch": epoch, "iteration": iteration,
                           "epoch_finished": epoch_finished},
                     sync=True, mesh=mesh_lib.mesh_metadata(self.mesh))
        except Exception:
            # going down either way; the newest committed snapshot
            # remains the resume point
            log.exception("grace-budget preemption checkpoint failed")
        model = self.model
        model.params, model.net_state, model.opt_state = _clone_tree(
            (params, net_state, opt_state))
        model.finished_iterations = iteration
        raise TrainingPreempted(
            f"training preempted by SIGTERM; grace budget {grace:g}s is "
            f"shorter than the ~{eta:.2f}s to the next step boundary — "
            f"mid-epoch checkpoint cut at iteration {iteration}")

    def _fault_input(self) -> np.ndarray:
        """Host-side ``train.grads`` fault scheduling: one site call per
        dispatched optimizer step. Returns the ``[code, scale]`` pair the
        compiled step consumes (``anomaly.inject_grads``) — zeros (the
        shared no-fault constant) unless an active plan fires a
        nan_loss/nan_grad/spike spec at this call index."""
        spec = faults.inject("train.grads")
        if spec is None:
            return _NO_FAULT
        code = anomaly.FAULT_CODES.get(spec.kind)
        if code is None:        # e.g. a latency spec: already applied
            return _NO_FAULT
        return np.asarray([code, spec.scale], np.float32)

    def _try_resume(self, mgr: CheckpointManager, params, opt_state,
                    net_state, psh, repl, allow_regress: bool = False):
        """Restore the newest VALID snapshot (``Topology.scala:1220-1246``
        + manifest/checksum verification): a corrupt or uncommitted
        snapshot is quarantined and the restore falls back to the next
        one that verifies, so resume always lands on good weights.
        Returns (params, opt_state, net_state, meta) — inputs unchanged
        if there is nothing at or past the model's in-memory progress
        (never regress: a snapshot older than ``finished_iterations`` was
        cut mid-epoch before further completed epochs).

        **Elastic restore**: snapshot leaves are host-side and
        topology-free, so the restored trees are explicitly RE-PLACED
        under the CURRENT mesh — params under ``psh`` (computed by
        ``mesh_lib.param_shardings`` for this mesh, which re-validates
        divisibility with the coalesced replicated-fallback warning),
        net state replicated, optimizer state re-sharded through
        ``_shard_opt_state`` (ZeRO moments re-partition over the new
        ``data`` axis). A preempted ``{data:8}`` job therefore resumes
        on ``{data:4}`` or ``{data:1}`` with bit-identical host values;
        a mesh-metadata mismatch is REPORTED (log + ``ckpt.elastic_restore``
        event), never silently mis-sharded."""
        # allow_regress (the rollback path): going BACK past the model's
        # in-memory progress is the point — the in-memory state is the
        # diverging one being abandoned. The default keeps the
        # never-regress guard (a stale mid-epoch snapshot must not undo
        # later completed epochs on an ordinary resume/retry).
        out = mgr.restore_latest(
            {"params": params, "opt_state": opt_state,
             "net_state": net_state},
            min_step=None if allow_regress
            else self.model.finished_iterations)
        if out is None:
            return params, opt_state, net_state, None
        step, trees, meta = out
        saved_mesh = meta.get("mesh")
        cur_mesh = mesh_lib.mesh_metadata(self.mesh)
        if saved_mesh is not None and saved_mesh != cur_mesh:
            log.warning(
                "elastic restore: ckpt-%d was saved under mesh %s "
                "(%s device(s)) and is restoring under mesh %s "
                "(%d device(s)) — host leaves re-placed under the "
                "current shardings, optimizer state re-sharded",
                step, mesh_lib.format_mesh(saved_mesh),
                saved_mesh.get("devices", "?"),
                mesh_lib.format_mesh(cur_mesh), cur_mesh["devices"])
            self._registry.emit(
                "ckpt.elastic_restore", step=step,
                saved=mesh_lib.format_mesh(saved_mesh),
                restored=mesh_lib.format_mesh(cur_mesh))
        params = jax.device_put(trees["params"], psh)
        opt_state = self._shard_opt_state(trees["opt_state"], psh, repl)
        net_state = jax.device_put(trees["net_state"], repl)
        log.info("resumed from checkpoint ckpt-%d (epoch %s)", step,
                 meta.get("epoch"))
        return params, opt_state, net_state, meta

    # -- fit ---------------------------------------------------------------
    def fit(self, x, y, *, batch_size: int, nb_epoch: int,
            validation_data=None, rng=None,
            callbacks: Sequence[Callable[[Dict[str, Any]], None]] = (),
            shuffle: bool = True, end_trigger: Optional[Trigger] = None,
            ) -> Dict[str, List[float]]:
        ctx = get_zoo_context()
        fs = FeatureSet.array(x, y, shuffle=shuffle, seed=ctx.seed)
        return self.fit_feature_set(fs, batch_size=batch_size,
                                    nb_epoch=nb_epoch,
                                    validation_data=validation_data, rng=rng,
                                    callbacks=callbacks,
                                    end_trigger=end_trigger)

    def fit_feature_set(self, fs: FeatureSet, *, batch_size: int,
                        nb_epoch: int, validation_data=None, rng=None,
                        callbacks: Sequence[Callable] = (),
                        end_trigger: Optional[Trigger] = None,
                        ) -> Dict[str, List[float]]:
        """Train on a FeatureSet with retry-on-failure semantics
        (``Topology.scala:1171-1253``): any step failure reloads the latest
        checkpoint (when ``set_checkpoint`` is configured) and retries, at
        most ``zoo.failure.retry_times`` times per
        ``zoo.failure.retry_window_sec`` window."""
        ctx = get_zoo_context()
        retry_times = int(ctx.get("zoo.failure.retry_times", 5))
        window_sec = float(ctx.get("zoo.failure.retry_window_sec", 3600))
        attempts = 0
        window_start = time.time()
        # per-fit self-healing state (zoo.train.sentinel=recover): the
        # flagged-iteration set survives rollback attempts within this
        # fit (the replay must skip the offending window), and the
        # rollback RetryBudget bounds escalations so a persistent
        # divergence raises TrainingDiverged instead of looping forever
        sen = self._sentinel_config()
        self._anomalous_steps = set()
        self._rollback_pending = False
        self._gp_restarting = False
        self._rollback_budget = (
            RetryBudget(capacity=sen.max_rollbacks, deposit=0.0,
                        name="train.rollback", registry=self._registry)
            if sen.mode == "recover" else None)
        # the epoch target is fixed once, after any checkpoint resume inside
        # the first attempt — retries must not extend it
        target_holder: Dict[str, int] = {}
        # one-shot profiler capture (model.set_profile): trace this fit
        # call, retries included (profiling.trace no-ops on None)
        profile_dir = getattr(self.model, "_profile_dir", None)
        if profile_dir:
            self.model._profile_dir = None
        # preemption-safe shutdown (zoo.checkpoint.on_sigterm, opt-in):
        # SIGTERM during this fit requests one final synchronous snapshot
        # at the next step boundary, then exits via TrainingPreempted —
        # the TPU-preemption analogue of the reference's driver-failure
        # snapshot. Signal handlers only install on the main thread.
        self._preempted.clear()
        sig_installed = False
        prev_handler = None
        self._sigterm_grace = None
        self._segment_t0 = self._segment_est = None
        self._boundary_ref = None
        if (bool(ctx.get("zoo.checkpoint.on_sigterm", False))
                and getattr(self.model, "_checkpoint", None) is not None):
            if threading.current_thread() is threading.main_thread():
                prev_handler = signal.signal(signal.SIGTERM,
                                             self._on_sigterm)
                sig_installed = True
                # grace budget: with the estimated time-to-boundary
                # above this, the handler cuts a MID-EPOCH snapshot
                # immediately instead of waiting out a dispatch the
                # preemption deadline may not cover. Armed ONLY with the
                # handler installed — the segment tracking clones whole
                # param trees, a price with no payoff when no handler
                # can ever fire.
                grace = float(ctx.get("zoo.checkpoint.sigterm_grace_s", 0)
                              or 0)
                self._sigterm_grace = grace if grace > 0 else None
            else:
                log.warning("zoo.checkpoint.on_sigterm is set but fit() "
                            "is not on the main thread; SIGTERM "
                            "checkpointing disabled for this fit")
        from ....utils import profiling
        # goodput/badput ledger for this fit (zoo.goodput.enabled):
        # every wall-clock second between here and the finally below is
        # attributed to exactly one category
        from ....observability.goodput import GoodputLedger, goodput_enabled
        self._goodput = (GoodputLedger("train", registry=self._registry)
                         if goodput_enabled() else None)
        if self._goodput is not None:
            self._goodput.open()
        try:
            with profiling.trace(profile_dir), span("train.fit",
                                                    registry=self._registry):
                return self._fit_with_retry(
                    fs, batch_size=batch_size, nb_epoch=nb_epoch,
                    target_holder=target_holder,
                    validation_data=validation_data, rng=rng,
                    callbacks=callbacks, end_trigger=end_trigger,
                    retry_times=retry_times, window_sec=window_sec,
                    attempts=attempts, window_start=window_start)
        finally:
            # close the ledger's last open interval — teardown is idle
            self._gp_note("idle")
            # the boundary clone holds whole param trees — never past fit
            self._boundary_ref = None
            self._segment_t0 = None
            if sig_installed:
                # getsignal/signal return None for a handler not installed
                # from Python (an embedding runtime's C-level handler) —
                # None is not re-installable; SIG_DFL is the closest we
                # can restore without raising out of this finally
                signal.signal(signal.SIGTERM,
                              prev_handler if prev_handler is not None
                              else signal.SIG_DFL)

    def _fit_with_retry(self, fs, *, batch_size, nb_epoch, target_holder,
                        validation_data, rng, callbacks, end_trigger,
                        retry_times, window_sec, attempts, window_start):
        while True:
            try:
                history = self._fit_impl(fs, batch_size=batch_size,
                                         nb_epoch=nb_epoch,
                                         target_holder=target_holder,
                                         validation_data=validation_data,
                                         rng=rng, callbacks=callbacks,
                                         end_trigger=end_trigger)
                # end-of-fit join of the async checkpoint writer: a
                # background save failure surfaces HERE (CheckpointSaveError
                # → the generic handler below, which re-cuts the lost
                # snapshot through the normal retry path)
                self._close_active_ckpt_mgr(surface=True)
                return history
            except KeyboardInterrupt:
                self._close_active_ckpt_mgr(surface=False)
                raise
            except _RollbackRequested as rb:
                # skip-budget escalation (zoo.train.sentinel=recover):
                # reload the last good snapshot and replay with the
                # flagged window skipped — bounded by the per-fit
                # rollback RetryBudget so a divergence the rollback
                # cannot outrun fails loudly instead of looping forever
                self._close_active_ckpt_mgr(surface=False)
                mgr = self._ckpt_manager()
                if mgr is None or mgr.latest() is None:
                    raise TrainingDiverged(
                        f"{rb} — and no checkpoint is configured/"
                        f"committed to roll back to "
                        f"(model.set_checkpoint enables recovery)") from rb
                budget = self._rollback_budget
                if budget is None or not budget.withdraw():
                    raise TrainingDiverged(
                        f"{rb} — rollback budget exhausted "
                        f"(zoo.train.max_rollbacks); the model holds the "
                        f"last known-good state") from rb
                # unwind cost up to here is replay overhead on the ledger
                self._gp_note("rollback_replay")
                self._m_rollback.inc()
                self._registry.emit("train.rollback", epoch=rb.epoch,
                                    skips=rb.skips,
                                    restore_step=mgr.latest(),
                                    skipped_iters=len(self._anomalous_steps))
                log.warning(
                    "training diverging (%s); rolling back to ckpt-%s and "
                    "replaying with %d flagged step(s) skipped", rb,
                    mgr.latest(), len(self._anomalous_steps))
                # the next _fit_impl attempt restores via _try_resume —
                # with regression past the in-memory progress allowed
                # (rolling BACK is the point) — and skips
                # self._anomalous_steps on replay
                self._rollback_pending = True
            except (ValueError, TypeError):
                # user/config errors are not transient — the reference likewise
                # excludes IllegalArgumentException from its retry loop
                # (Topology.scala:1171-1253)
                self._close_active_ckpt_mgr(surface=False)
                raise
            except Exception:
                self._close_active_ckpt_mgr(surface=False)
                mgr = self._ckpt_manager()
                if mgr is None or mgr.latest() is None:
                    raise  # nothing to recover from
                if time.time() - window_start > window_sec:
                    attempts = 0
                    window_start = time.time()
                attempts += 1
                if attempts > retry_times:
                    log.exception("giving up after %d failed attempts", attempts)
                    raise
                log.warning("training step failed (attempt %d/%d); reloading "
                            "latest checkpoint and retrying", attempts,
                            retry_times, exc_info=True)
                # failed-attempt unwind + upcoming reload is restart cost
                self._gp_note("restart")
                self._gp_restarting = True
                # the next _fit_impl attempt restores params/opt_state from
                # the latest snapshot via _try_resume
            except BaseException:
                # TrainingPreempted (SystemExit): the final sync snapshot is
                # already committed — just release the writer and exit
                self._close_active_ckpt_mgr(surface=False)
                raise

    def _fit_impl(self, fs: FeatureSet, *, batch_size: int, nb_epoch: int,
                  target_holder: Dict[str, int], validation_data=None,
                  rng=None, callbacks: Sequence[Callable] = (),
                  end_trigger: Optional[Trigger] = None,
                  ) -> Dict[str, List[float]]:
        ctx = get_zoo_context()
        model = self.model
        # fail NOW, not after an epoch of compute: scan fusing stacks K
        # consecutive batches into one array (can't mix widths), and
        # validation/evaluate need one dense array
        if (getattr(fs, "ragged", False)
                and int(ctx.get("zoo.train.scan_steps", 1)) > 1):
            raise ValueError(
                "bucketed (ragged) datasets cannot use "
                "zoo.train.scan_steps > 1 — fused chunks stack same-shape "
                "batches; set scan_steps=1")
        if getattr(validation_data, "ragged", False):
            raise ValueError(
                "bucketed validation_data is not supported — evaluate per "
                "bucket (validation_data.buckets) instead")
        if (getattr(self.loss, "__name__", "") == "rank_hinge"
                and getattr(fs, "shuffle", False)):
            log.warning(
                "rank_hinge consumes consecutive (positive, negative) rows, "
                "but this FeatureSet shuffles — the pairing is scrambled and "
                "the loss is meaningless; train with "
                "FeatureSet.array(..., shuffle=False)")
        dp = mesh_lib.data_parallel_size(self.mesh)
        if batch_size % dp != 0:
            rounded = _round_up(batch_size, dp)
            log.warning("batch_size %d not divisible by data-parallel size %d; "
                        "rounding up to %d", batch_size, dp, rounded)
            batch_size = rounded

        # K>1 runs K optimizer steps per dispatch via lax.scan
        # (zoo.train.scan_steps); triggers are then observed at chunk
        # boundaries (see _fired_within)
        scan_steps = max(1, int(ctx.get("zoo.train.scan_steps", 1)))

        # anomaly sentinels (docs/guides/TRAINING.md): resolved once per
        # loop; active ⇒ the steps carry EWMA state + packed flags and
        # the host runs a lagged flag monitor
        sen = self._sentinel_config()
        monitor = _SentinelMonitor(self, sen) if sen.active else None

        if model.params is None:
            model.init_weights(rng=rng, sample_input=fs.sample(1))
        if scan_steps > 1 and self._scan_step is None:
            self.build_scan_step()
        if self._train_step is None:
            self.build_train_step()

        repl = mesh_lib.replicated_sharding(self.mesh)
        # params: replicated under pure DP; sharded over the model axis when
        # the mesh has one (layers declare the specs — SURVEY §2.4 TP)
        psh = mesh_lib.param_shardings(model, model.params, self.mesh)
        # clone: the donated train step must own its buffers exclusively —
        # without the copy, device_put of an already-replicated model.params
        # is a no-op alias and step 1 would delete the model's weights
        params = jax.device_put(_clone_tree(model.params), psh)
        net_state = jax.device_put(_clone_tree(model.net_state), repl)
        # eval_shape: the CURRENT optimizer's state structure, zero allocation
        fresh_struct = jax.tree_util.tree_structure(
            jax.eval_shape(self.optimizer.init, params))
        if model.opt_state is not None:
            # reuse stored optimizer state only when it structurally matches
            # the CURRENT optimizer — a clipping/optimizer change between
            # train calls (Estimator.scala:75-100) alters the optax state
            # tree, and feeding the old one would corrupt the update
            same = (jax.tree_util.tree_structure(model.opt_state)
                    == fresh_struct)
            if same:
                opt_state = self._shard_opt_state(
                    _clone_tree(model.opt_state), psh, repl)
            else:
                log.warning("optimizer structure changed since the last fit; "
                            "resetting optimizer state")
                opt_state = self._shard_opt_state(
                    self.optimizer.init(params), psh, repl)
        else:
            opt_state = self._shard_opt_state(self.optimizer.init(params),
                                              psh, repl)

        # resume: if a checkpoint directory is configured and holds a snapshot
        # newer than this model's progress, restore it (process-death resume)
        mgr = self._ckpt_manager()
        # registered so _fit_with_retry can join/close the async writer on
        # every exit path (including exceptions and preemption)
        self._active_ckpt_mgr = mgr
        ckpt_trigger = self._ckpt_trigger()
        if mgr is not None:
            rollback = self._rollback_pending
            self._rollback_pending = False
            params, opt_state, net_state, meta = self._try_resume(
                mgr, params, opt_state, net_state, psh, repl,
                allow_regress=rollback)
            # restore work belongs to the recovery path that demanded
            # it; a clean first attempt's resume probe is just spin-up
            self._gp_note("rollback_replay" if rollback
                          else "restart" if self._gp_restarting
                          else "idle")
            self._gp_restarting = False
            if meta is not None and meta.get("epoch") is not None:
                resumed_epoch = int(meta["epoch"]) - (
                    0 if meta.get("epoch_finished") else 1)
                # a rollback REGRESSES the in-memory progress to the
                # restored snapshot — the abandoned later epochs retrain
                # (with the flagged windows skipped)
                if rollback or resumed_epoch > model.finished_epochs:
                    model.finished_epochs = resumed_epoch
                model.finished_iterations = int(meta.get(
                    "iteration", model.finished_iterations))
            elif rollback:
                log.warning("rollback requested but no snapshot could be "
                            "restored; continuing from the in-memory "
                            "state (further anomalies will re-escalate "
                            "within the rollback budget)")
        # sliced disk tier: one loop "epoch" is ONE slice pass; nb_epoch and
        # EveryEpoch-style triggers count FULL passes of num_of_slice slices
        # (DiskFeatureSet + ZooTrigger.scala:44-66 slice awareness)
        n_slices = int(getattr(fs, "num_of_slice", 1) or 1)
        if n_slices > 1:
            def slice_aware(trig):
                if isinstance(trig, EveryEpoch):
                    return _FullPassEveryEpoch(n_slices)
                if isinstance(trig, MaxEpoch):
                    return MaxEpoch(trig.max_epoch * n_slices)
                if trig is not None and not isinstance(
                        trig, (SeveralIteration, _FullPassEveryEpoch)):
                    log.warning("trigger %s under a %d-slice DiskFeatureSet "
                                "observes SLICE passes as epochs, not full "
                                "passes", type(trig).__name__, n_slices)
                return trig
            ckpt_trigger = slice_aware(ckpt_trigger)
            end_trigger = slice_aware(end_trigger)
        if "target" not in target_holder:
            # "train nb_epoch more" counts from post-resume progress, matching
            # the reference's getFinishedEpoch continuation (Topology.scala:373-386)
            target_holder["target"] = (model.finished_epochs
                                       + nb_epoch * n_slices)
        target_epoch = target_holder["target"]

        # device-cache fast path: dataset lives in HBM, one dispatch per epoch
        device_cache = bool(ctx.get("zoo.train.device_cache", False))
        if device_cache and sen.active:
            # sentinels observe per-step flags at dispatch boundaries and
            # recovery needs the host in the loop; a whole-epoch dispatch
            # would defer both to epoch granularity — fall back to the
            # streamed path (documented in TRAINING.md)
            log.warning(
                "zoo.train.device_cache disabled for this fit: the "
                "anomaly-sentinel/grad-clip layer is active "
                "(zoo.train.sentinel=%s, zoo.train.grad_clip=%g); using "
                "the streamed dispatch path", sen.mode, sen.grad_clip)
            device_cache = False
        epoch_fn = None
        xs_dev = ys_dev = None
        # n_slices first: DiskFeatureSet.y is a property that would gather
        # the whole label file just to answer the None check
        if (device_cache and n_slices <= 1
                and getattr(fs, "device_cacheable", True)
                and fs.y is not None):
            n_steps = fs.steps_per_epoch(batch_size, drop_last=True)
            for trig, role in ((ckpt_trigger, "checkpoint"),
                               (end_trigger, "end")):
                if (isinstance(trig, SeveralIteration)
                        and trig.interval < n_steps):
                    log.warning(
                        "device_cache runs one dispatch per epoch, so the %s "
                        "trigger SeveralIteration(%d) is only observed at "
                        "epoch boundaries (%d steps) — up to %d steps late",
                        role, trig.interval, n_steps,
                        n_steps - trig.interval)
            # the shuffled gather only reads indices < len(fs), so padding
            # rows (needed to make the leading dim shardable over dp) are
            # never selected
            n_padded = _round_up(len(fs), dp)

            def put(a):
                # device-resident inputs (extract→fit chain) pad and
                # relayout ON DEVICE — no host round trip
                return jax.device_put(jnp.asarray(_pad_to(a, n_padded)),
                                      mesh_lib.batch_sharding(self.mesh))

            epoch_fn = self.build_epoch_fn(len(fs), batch_size, n_steps,
                                           shuffle=fs.shuffle)
            cache_key = (id(fs), len(fs), n_padded)
            if cache_key not in self._data_cache:
                # keep only the latest dataset resident (HBM is the scarce
                # resource; switching sets back and forth re-uploads)
                self._data_cache.clear()
                self._data_cache[cache_key] = (fs, jax.tree.map(put, fs.x),
                                               jax.tree.map(put, fs.y))
            _, xs_dev, ys_dev = self._data_cache[cache_key]

        base_rng = rng if rng is not None else ctx.rng()
        throttle_cpu = jax.default_backend() == "cpu"
        # sentinel EWMA carry (device scalars) — fresh per fit attempt:
        # after a rollback the restored params' gradient scale is the
        # baseline worth learning, not the diverging run's
        sstate = anomaly.init_state() if sen.active else None
        # the no-fault input for scan chunks, allocated ONCE per fit and
        # sliced per dispatch (the single-step path shares _NO_FAULT)
        no_fault_chunk = (np.zeros((scan_steps, 2), np.float32)
                          if sen.active and scan_steps > 1 else None)
        history: Dict[str, List[float]] = {"loss": []}
        loop_state = TrainLoopState(iteration=model.finished_iterations,
                                    epoch=model.finished_epochs + 1)
        stop = False

        # fused-epoch fast path: K epochs per dispatch. Only when nothing
        # needs the host between epochs — no checkpointing, validation, or
        # end trigger (nb_epoch still bounds the run); per-epoch losses and
        # records come out identical to the per-epoch path (same rng
        # schedule), only the wall timing is amortized across the block.
        fuse = int(ctx.get("zoo.train.fuse_epochs", 1))
        if (epoch_fn is not None and fuse > 1 and mgr is None
                and validation_data is None and end_trigger is None):
            n_steps = fs.steps_per_epoch(batch_size, drop_last=True)
            tb = getattr(model, "_train_summary", None)
            epoch = model.finished_epochs
            while epoch < target_epoch:
                g = min(fuse, target_epoch - epoch)
                t0 = time.time()
                it0 = jnp.asarray(loop_state.iteration, jnp.int32)
                if g == 1:
                    shuffle_rng = jax.random.key(
                        fs.seed + ctx.seed + epoch + 1)
                    t0 += self._maybe_compute_flops(
                        epoch_fn, (params, opt_state, net_state, base_rng,
                                   it0, shuffle_rng, xs_dev, ys_dev),
                        n_steps * batch_size)
                    params, opt_state, net_state, L = epoch_fn(
                        params, opt_state, net_state, base_rng, it0,
                        shuffle_rng, xs_dev, ys_dev)
                else:
                    mfn = self.build_multi_epoch_fn(
                        len(fs), batch_size, n_steps, fs.shuffle, g)
                    keys = jnp.stack(
                        [jax.random.key(fs.seed + ctx.seed + e)
                         for e in range(epoch + 1, epoch + g + 1)])
                    t0 += self._maybe_compute_flops(
                        mfn, (params, opt_state, net_state, base_rng, it0,
                              keys, xs_dev, ys_dev),
                        g * n_steps * batch_size)
                    params, opt_state, net_state, L = mfn(
                        params, opt_state, net_state, base_rng, it0, keys,
                        xs_dev, ys_dev)
                L = np.asarray(jax.block_until_ready(L)).reshape(g, -1)
                dt = (time.time() - t0) / g
                self._observe_fit_metrics(g * n_steps, dt * g,
                                          g * n_steps * batch_size)
                loop_state.iteration += g * n_steps
                # publish once per block: the intermediate epochs' params
                # never materialize on the host (that is the point)
                model.params, model.net_state, model.opt_state = _clone_tree(
                    (params, net_state, opt_state))
                model.finished_iterations = loop_state.iteration
                thr = (n_steps * batch_size / dt) if dt > 0 else 0.0
                lr = getattr(model, "_lr", None)
                # every epoch inside a fused block completes by construction
                loop_state.epoch_finished = True
                for j in range(g):
                    e = epoch + 1 + j
                    last = j == g - 1
                    epoch_loss = float(L[j].mean())
                    history["loss"].append(epoch_loss)
                    model.finished_epochs = e
                    loop_state.epoch = e
                    it_e = loop_state.iteration - (g - 1 - j) * n_steps
                    # intermediate epochs' weights never materialize on the
                    # host (that is the point of fusing) — their records say
                    # so with None rather than smuggling end-of-block params
                    # under an earlier epoch number
                    record = {"epoch": e, "loss": epoch_loss,
                              "iteration": it_e, "throughput": thr,
                              "params": model.params if last else None,
                              "opt_state": model.opt_state if last else None,
                              "net_state": model.net_state if last else None,
                              "loop_state": loop_state}
                    if tb is not None:
                        for k2, lv in enumerate(L[j]):
                            tb.add_scalar("Loss", float(lv),
                                          it_e - n_steps + k2 + 1)
                        tb.add_scalar("Throughput", thr, it_e)
                        if callable(lr):
                            tb.add_scalar("LearningRate", float(lr(it_e)),
                                          it_e)
                        elif isinstance(lr, (int, float)):
                            tb.add_scalar("LearningRate", float(lr), it_e)
                        if last:
                            _write_param_histograms(
                                tb, model.params,
                                range(epoch + 1, epoch + g + 1), it_e,
                                n_steps=n_steps)
                        tb.writer.flush()
                    log.info("Epoch %d: loss=%.6f (%.1f ex/s)", e,
                             epoch_loss, thr)
                    for cb in callbacks:
                        cb(record)
                epoch += g
            return history

        epoch = model.finished_epochs  # so nb_epoch=0 is a clean no-op
        for epoch in range(model.finished_epochs + 1, target_epoch + 1):
            # epoch-boundary overhead (metrics, callbacks, validation of
            # the previous epoch) since the last step lands on idle
            self._gp_note("idle")
            t0 = time.time()
            losses = []
            n_seen = 0
            loop_state.epoch = epoch
            # clear the boundary flag: mid-epoch trigger checks must not see
            # the previous epoch's True (stale EveryEpoch/MaxEpoch fires)
            loop_state.epoch_finished = False
            if monitor is not None:
                monitor.begin_epoch(epoch, loop_state.iteration)
            if epoch_fn is not None:
                prev_iter = loop_state.iteration
                shuffle_rng = jax.random.key(fs.seed + ctx.seed + epoch)
                it0 = jnp.asarray(prev_iter, jnp.int32)
                n_steps = fs.steps_per_epoch(batch_size, drop_last=True)
                t0 += self._maybe_compute_flops(
                    epoch_fn, (params, opt_state, net_state, base_rng, it0,
                               shuffle_rng, xs_dev, ys_dev),
                    n_steps * batch_size)
                self._segment_begin(mgr, loop_state, params, opt_state,
                                    net_state)
                params, opt_state, net_state, l = epoch_fn(
                    params, opt_state, net_state, base_rng, it0, shuffle_rng,
                    xs_dev, ys_dev)
                self._segment_end()
                self._gp_note("device_step")   # whole-epoch dispatch
                losses.append(l)
                loop_state.iteration += n_steps
                n_seen += n_steps * batch_size
                if mgr is not None and _fired_within(ckpt_trigger, loop_state,
                                                     prev_iter):
                    self._save_checkpoint(mgr, loop_state, params, opt_state,
                                          net_state)
                self._maybe_preempt(mgr, loop_state, params, opt_state,
                                    net_state)
                if _fired_within(end_trigger, loop_state, prev_iter):
                    stop = True
                stream = ()
            elif scan_steps > 1:
                batches = fs.iter_batches(batch_size, epoch=ctx.seed + epoch,
                                          drop_last=True)
                stream = prefetch_to_device(
                    _chunked(batches, scan_steps), self.mesh,
                    sharding=mesh_lib.stacked_batch_sharding(self.mesh),
                    ledger=self._goodput)
            else:
                batches = fs.iter_batches(batch_size, epoch=ctx.seed + epoch,
                                          drop_last=True)
                stream = prefetch_to_device(batches, self.mesh,
                                            ledger=self._goodput)
            for bx_d, by_d in stream:
                prev_iter = loop_state.iteration
                k = jax.tree.leaves(bx_d)[0].shape[0] if scan_steps > 1 \
                    else 1
                if (monitor is not None and self._anomalous_steps
                        and any(monitor.step_key(prev_iter + j)
                                in self._anomalous_steps
                                for j in range(k))):
                    # rollback replay: the offending data window is NOT
                    # re-dispatched (its steps were flagged before the
                    # rollback); iteration still advances so the rng
                    # schedule and trigger windows stay aligned with the
                    # original attempt
                    loop_state.iteration += k
                    monitor.note_replay_skip(k)
                    self._gp_note("anomaly_skip")
                    if mgr is not None and _fired_within(
                            ckpt_trigger, loop_state, prev_iter):
                        self._save_checkpoint(mgr, loop_state, params,
                                              opt_state, net_state)
                    self._maybe_preempt(mgr, loop_state, params, opt_state,
                                        net_state)
                    if _fired_within(end_trigger, loop_state, prev_iter):
                        stop = True
                        break
                    continue
                if scan_steps > 1:
                    it0 = jnp.asarray(prev_iter, jnp.int32)
                    if monitor is None:
                        t0 += self._maybe_compute_flops(
                            self._scan_step,
                            (params, opt_state, net_state, base_rng, it0,
                             bx_d, by_d), k * batch_size)
                        self._segment_begin(mgr, loop_state, params,
                                            opt_state, net_state)
                        params, opt_state, net_state, l = self._scan_step(
                            params, opt_state, net_state, base_rng, it0,
                            bx_d, by_d)
                        self._segment_end()
                    else:
                        fault = (np.stack([self._fault_input()
                                           for _ in range(k)])
                                 if sen.faults
                                 else no_fault_chunk[:k])
                        t0 += self._maybe_compute_flops(
                            self._scan_step,
                            (params, opt_state, net_state, sstate,
                             base_rng, it0, bx_d, by_d, fault),
                            k * batch_size)
                        self._segment_begin(mgr, loop_state, params,
                                            opt_state, net_state)
                        (params, opt_state, net_state, sstate, l,
                         flags) = self._scan_step(
                             params, opt_state, net_state, sstate,
                             base_rng, it0, bx_d, by_d, fault)
                        self._segment_end()
                        monitor.push(prev_iter, flags)
                    loop_state.iteration += k
                    n_seen += k * batch_size
                else:
                    step_rng = jax.random.fold_in(base_rng, prev_iter)
                    if monitor is None:
                        t0 += self._maybe_compute_flops(
                            self._train_step,
                            (params, opt_state, net_state, step_rng, bx_d,
                             by_d), batch_size)
                        self._segment_begin(mgr, loop_state, params,
                                            opt_state, net_state)
                        params, opt_state, net_state, l = self._train_step(
                            params, opt_state, net_state, step_rng, bx_d,
                            by_d)
                        self._segment_end()
                    else:
                        fault = (self._fault_input() if sen.faults
                                 else _NO_FAULT)
                        t0 += self._maybe_compute_flops(
                            self._train_step,
                            (params, opt_state, net_state, sstate,
                             step_rng, fault, bx_d, by_d), batch_size)
                        self._segment_begin(mgr, loop_state, params,
                                            opt_state, net_state)
                        (params, opt_state, net_state, sstate, l,
                         flags) = self._train_step(
                             params, opt_state, net_state, sstate,
                             step_rng, fault, bx_d, by_d)
                        self._segment_end()
                        monitor.push(prev_iter, flags)
                    loop_state.iteration += 1
                    n_seen += batch_size
                losses.append(l)
                # XLA:CPU only — bound host run-ahead. Its in-process
                # collective rendezvous aborts (40 s timeout) when dozens
                # of slow queued programs starve some device threads;
                # blocking every few dispatches caps the queue. Real TPU
                # runtimes pipeline deeply and stay unthrottled.
                if throttle_cpu and len(losses) % 4 == 0:
                    jax.block_until_ready(l)
                if mgr is not None and _fired_within(ckpt_trigger, loop_state,
                                                     prev_iter):
                    self._save_checkpoint(mgr, loop_state, params, opt_state,
                                          net_state)
                self._maybe_preempt(mgr, loop_state, params, opt_state,
                                    net_state)
                if _fired_within(end_trigger, loop_state, prev_iter):
                    stop = True
                    break
            completed = not stop  # stop=True means the epoch was cut short
            if monitor is not None:
                # drain every pending flag first (escalation may raise
                # here, BEFORE the boundary checkpoint below); in recover
                # mode skipped steps' losses were never applied and are
                # excluded from the epoch mean
                lv = (np.concatenate([np.atleast_1d(np.asarray(l))
                                      for l in losses])
                      if losses else np.zeros(0, np.float32))
                lmask = monitor.loss_mask(len(lv))
                epoch_loss = (float(lv[lmask].mean()) if lmask.any()
                              else float("nan"))
            else:
                epoch_loss = (float(jnp.mean(jnp.concatenate(
                    [jnp.atleast_1d(l) for l in losses])))
                    if losses else float("nan"))
            dt = time.time() - t0
            self._observe_fit_metrics(n_seen // batch_size, dt, n_seen)
            history["loss"].append(epoch_loss)
            loop_state.epoch_finished = completed
            if hasattr(end_trigger, "record"):
                end_trigger.record(epoch_loss)
            # cut a snapshot at the trigger, or unconditionally on a mid-epoch
            # stop so the truncated epoch's progress survives (its meta says
            # epoch_finished=False, so a resume retrains that epoch)
            if mgr is not None and (stop or ckpt_trigger(loop_state)):
                self._save_checkpoint(mgr, loop_state, params, opt_state,
                                      net_state)

            # publish progress every epoch — clones, because the live trees
            # feed the donating train step next epoch; this is also what a
            # retry attempt falls back to when the newest snapshot is older
            model.params, model.net_state, model.opt_state = \
                _clone_tree((params, net_state, opt_state))
            if completed:
                model.finished_epochs = epoch
            model.finished_iterations = loop_state.iteration

            record = {"epoch": epoch, "loss": epoch_loss,
                      "iteration": loop_state.iteration,
                      "throughput": n_seen / dt if dt > 0 else 0.0,
                      "params": model.params, "opt_state": model.opt_state,
                      "net_state": model.net_state, "loop_state": loop_state}
            val = None
            if validation_data is not None:
                if isinstance(validation_data, FeatureSet):
                    vx, vy = validation_data.x, validation_data.y
                else:
                    vx, vy = validation_data
                val = self.evaluate(vx, vy, batch_size=batch_size)
                for k, v in val.items():
                    history.setdefault("val_" + k, []).append(v)
                record.update({"val_" + k: v for k, v in val.items()})
            tb = getattr(model, "_train_summary", None)
            if tb is not None:
                # one Loss point per optimizer step (the reference's
                # per-iteration granularity), written at epoch end so no
                # device sync lands inside the dispatch pipeline
                loss_vec = (np.concatenate(
                    [np.atleast_1d(np.asarray(l)) for l in losses])
                    if losses else np.zeros(0))
                if (monitor is not None
                        and len(monitor.epoch_step_iters) == len(loss_vec)):
                    # replay-skipped windows advance the iteration
                    # counter without recording losses — the monitor's
                    # per-step iteration log keeps each point on its
                    # real x position
                    loss_its = [i + 1 for i in monitor.epoch_step_iters]
                else:
                    start_it = loop_state.iteration - len(loss_vec)
                    loss_its = [start_it + j + 1
                                for j in range(len(loss_vec))]
                for j, lv in enumerate(loss_vec):
                    tb.add_scalar("Loss", float(lv), loss_its[j])
                tb.add_scalar("Throughput", record["throughput"],
                              loop_state.iteration)
                lr = getattr(model, "_lr", None)
                if callable(lr):
                    tb.add_scalar("LearningRate",
                                  float(lr(loop_state.iteration)),
                                  loop_state.iteration)
                elif isinstance(lr, (int, float)):
                    tb.add_scalar("LearningRate", float(lr),
                                  loop_state.iteration)
                if completed:
                    # a mid-epoch end_trigger stop retrains this epoch on
                    # the next fit(); logging its partial params here
                    # would put two histograms under one epoch number
                    _write_param_histograms(tb, model.params, (epoch,),
                                            loop_state.iteration,
                                            n_steps=len(loss_vec))
                tb.writer.flush()
            vtb = getattr(model, "_val_summary", None)
            if vtb is not None and val is not None:
                for k, v in val.items():
                    vtb.add_scalar(k, float(v), loop_state.iteration)
                vtb.writer.flush()
            log.info("Epoch %d%s: loss=%.6f (%.1f ex/s)%s", epoch,
                     "" if completed else " (stopped mid-epoch)", epoch_loss,
                     record["throughput"],
                     "".join(f" val_{k}={v:.4f}" for k, v in
                             (val.items() if val is not None else ())))
            for cb in callbacks:
                cb(record)
            # epoch_finished stays True through this boundary check (it is
            # cleared at the next epoch's start): MaxEpoch must see the
            # finished count, else a satisfied end trigger runs one extra
            # partial epoch
            if stop or (end_trigger is not None and end_trigger(loop_state)):
                break

        return history

    # -- evaluate / predict -------------------------------------------------
    def _padded_batches(self, x, y, eff_bs: int, dp: int, *, with_mask: bool):
        """Padded fixed-size batches (+ per-row validity mask) for eval and
        predict — the host-side generator behind the prefetch pipeline."""
        for bx, by in iter_batches(x, y, eff_bs, shuffle=False, seed=0,
                                   drop_last=False):
            n = _num_examples(bx)
            padded = _round_up(n, dp)
            if n != padded:
                bx = _pad_to(bx, padded)
                by = None if by is None else _pad_to(by, padded)
            if with_mask:
                # padded tail rows are masked out of every statistic
                mask = np.concatenate([np.ones(n, np.float32),
                                       np.zeros(padded - n, np.float32)])
                yield bx, by, mask
            else:
                yield bx

    def evaluate(self, x, y=None, *, batch_size: int = 32) -> Dict[str, float]:
        if isinstance(x, FeatureSet):
            x, y = x.x, x.y
        model = self.model
        if self._eval_step is None:
            self.build_eval_step()
        totals = None
        dp = mesh_lib.data_parallel_size(self.mesh)
        eff_bs = _round_up(max(batch_size, dp), dp)
        # stream through the same prefetch pipeline as training; keep the
        # running totals on device so no step blocks on a host sync
        steps = 0
        with span("train.evaluate", registry=self._registry):
            t0 = time.perf_counter()
            stream = prefetch_to_device(
                self._padded_batches(x, y, eff_bs, dp, with_mask=True),
                self.mesh)
            for bx_d, by_d, mask_d in stream:
                stats = self._eval_step(model.params, model.net_state, bx_d,
                                        by_d, mask_d)
                totals = stats if totals is None else jax.tree.map(
                    lambda a, b: a + b, totals, stats)
                steps += 1
            out = {}
            if totals is None:
                return out
            totals = jax.device_get(totals)
            # registry update (the eval twin of _observe_fit_metrics): one
            # weighted observation per streamed step, record count from the
            # mask sum so pad rows never inflate it
            dt = time.perf_counter() - t0
            if steps and dt > 0:
                self._m_eval_step_time.observe(dt / steps, n=steps)
            self._m_eval_records.inc(int(totals["loss"]["count"]))
        for m in self.metrics:
            out[m.name] = float(m.finalize(totals[m.name]))
        out["loss"] = float(totals["loss"]["sum"] / max(totals["loss"]["count"], 1.0))
        return out

    def predict(self, x, *, batch_size: int = 32):
        if isinstance(x, FeatureSet):
            x = x.x
        model = self.model
        if self._predict_step is None:
            self.build_predict_step()
        dp = mesh_lib.data_parallel_size(self.mesh)
        eff_bs = _round_up(max(batch_size, dp), dp)
        n_total = _num_examples(x)
        sizes = [min(eff_bs, n_total - i) for i in range(0, n_total, eff_bs)]
        # keep a small window of batches in flight: dispatch stays ahead of
        # the host transfer (no per-batch sync) while device memory stays
        # bounded at `window` batches instead of O(dataset)
        window = 4
        pending: collections.deque = collections.deque()
        outs = []

        def drain_one():
            yp, n = pending.popleft()
            outs.append(jax.tree.map(lambda a: a[:n], jax.device_get(yp)))

        with span("train.predict", registry=self._registry):
            t0 = time.perf_counter()
            stream = prefetch_to_device(
                self._padded_batches(x, None, eff_bs, dp, with_mask=False),
                self.mesh)
            for i, bx_d in enumerate(stream):
                pending.append((self._predict_step(
                    model.params, model.net_state, bx_d), sizes[i]))
                if len(pending) > window:
                    drain_one()
            while pending:
                drain_one()
            # registry update mirrors evaluate's: weighted per-batch step
            # time + the REAL example count (pads excluded by `sizes`)
            dt = time.perf_counter() - t0
            if sizes and dt > 0:
                self._m_predict_step_time.observe(dt / len(sizes),
                                                  n=len(sizes))
            self._m_predict_records.inc(n_total)
        if not outs:
            return None
        return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *outs)


def _first_dim(x):
    if isinstance(x, (list, tuple)):
        return x[0].shape[0]
    return x.shape[0]


# ---------------------------------------------------------------------------
# KerasNet facade: compile / fit / evaluate / predict
# (attached here so engine.py stays free of optimizer machinery)
# ---------------------------------------------------------------------------

def _compile(self: KerasNet, optimizer="adam", loss="mse", metrics=None,
             clip_norm: Optional[float] = None,
             clip_value: Optional[float] = None, **opt_kwargs):
    """``KerasNet.compile`` (``Topology.scala:135``)."""
    opt = optim_lib.get_optimizer(optimizer, **opt_kwargs)
    opt = optim_lib.with_clipping(opt, clip_norm=clip_norm, clip_value=clip_value)
    loss_fn = objectives.get_loss(loss)
    ms = [metrics_lib.get_metric(m) for m in (metrics or [])]
    self._compiled = CompiledSpec(opt, loss_fn, ms)
    self._loop = TrainingLoop(self, opt, loss_fn, ms)
    # effective lr (constant or schedule) for the LearningRate summary
    self._lr = optim_lib.resolve_lr(optimizer, **opt_kwargs)
    return self


def _init_weights(self: KerasNet, rng=None, input_shape=None, sample_input=None):
    """Materialize params/state. Shape comes from (in order) explicit
    ``input_shape``, a ``sample_input`` batch, or the declared layer shapes."""
    ctx = get_zoo_context()
    rng = rng if rng is not None else ctx.rng()
    shape = input_shape
    if shape is None and sample_input is not None:
        xs = sample_input if isinstance(sample_input, (list, tuple)) else [sample_input]
        shapes = [(None,) + tuple(np.asarray(a).shape[1:]) for a in xs]
        shape = shapes if len(shapes) > 1 else shapes[0]
    if shape is None:
        shape = self.input_shape
    params = self.build(rng, shape)
    state = self.initial_state(shape)
    self.params = params
    self.net_state = state
    return self


def _set_checkpoint(self: KerasNet, path: str, trigger: Optional[Trigger] = None,
                    keep: Optional[int] = None):
    """``KerasNet.setCheckpoint`` (``Topology.scala:245-255``): snapshot
    params + optimizer state + net state into ``path`` whenever ``trigger``
    fires (default: every epoch, ``Topology.scala:1161-1168``)."""
    self._checkpoint = {"path": path, "trigger": trigger, "keep": keep}
    return self


def _set_tensorboard(self: KerasNet, log_dir: str, app_name: str,
                     parameters_every_epochs: Optional[int] = None):
    """``setTensorBoard(logDir, appName)`` (``Topology.scala:204-216``):
    write train scalars (Loss per iteration, Throughput, LearningRate) to
    ``<log_dir>/<app_name>/train`` and validation metrics to
    ``.../validation`` as TensorBoard event files.

    ``parameters_every_epochs=N`` additionally writes per-layer weight
    HISTOGRAMS every N epochs (the reference's
    ``TrainSummary.setSummaryTrigger("Parameters", ...)`` +
    ``Summary.scala`` histogram path); under fused-epoch dispatch they
    land on the final epoch of each fused block, where the params are
    host-visible."""
    from ....utils.tensorboard import TrainSummary, ValidationSummary
    for attr in ("_train_summary", "_val_summary"):
        old = getattr(self, attr, None)
        if old is not None:  # redirecting: release the previous file handle
            old.close()
    self._train_summary = TrainSummary(log_dir, app_name)
    if parameters_every_epochs is not None:
        self._train_summary.set_summary_trigger("Parameters",
                                                parameters_every_epochs)
    self._val_summary = ValidationSummary(log_dir, app_name)
    return self


def _set_profile(self: KerasNet, log_dir: str):
    """Capture a ``jax.profiler`` trace of the NEXT ``fit`` call into
    ``log_dir`` (one-shot) — view with TensorBoard's profile plugin/xprof.
    The sampling-profiler capability the reference never had (SURVEY §5:
    "no sampling profiler, no trace files")."""
    self._profile_dir = log_dir
    return self


def _get_train_summary(self: KerasNet, tag: str = "Loss") -> np.ndarray:
    """``getTrainSummary(tag)`` (``Topology.scala:222-229``): (n, 3) rows of
    ``[iteration, value, wall_time]``."""
    if getattr(self, "_train_summary", None) is None:
        raise RuntimeError("call set_tensorboard() before reading summaries")
    return self._train_summary.read_scalar(tag)


def _get_validation_summary(self: KerasNet, tag: str) -> np.ndarray:
    """``getValidationSummary(tag)`` (``Topology.scala:231-236``)."""
    if getattr(self, "_val_summary", None) is None:
        raise RuntimeError("call set_tensorboard() before reading summaries")
    return self._val_summary.read_scalar(tag)


def _fit(self: KerasNet, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
         validation_data=None, shuffle: bool = True, rng=None, callbacks=(),
         end_trigger: Optional[Trigger] = None):
    """``KerasNet.fit`` (``Topology.scala:418``). ``x`` may be an array, a
    list of arrays (multi-input), or a FeatureSet (then ``y=None``)."""
    if self._compiled is None:
        raise RuntimeError("call compile() before fit()")
    if isinstance(x, FeatureSet):
        return self._loop.fit_feature_set(x, batch_size=batch_size,
                                          nb_epoch=nb_epoch,
                                          validation_data=validation_data,
                                          rng=rng, callbacks=callbacks,
                                          end_trigger=end_trigger)
    return self._loop.fit(x, y, batch_size=batch_size, nb_epoch=nb_epoch,
                          validation_data=validation_data, shuffle=shuffle,
                          rng=rng, callbacks=callbacks, end_trigger=end_trigger)


def _evaluate(self: KerasNet, x, y=None, batch_size: int = 32):
    """``KerasNet.evaluate`` (``Topology.scala:496``)."""
    if self._compiled is None:
        raise RuntimeError("call compile() before evaluate()")
    if self.params is None:
        raise RuntimeError("no weights; fit() or init_weights() first")
    return self._loop.evaluate(x, y, batch_size=batch_size)


def _predict(self: KerasNet, x, batch_size: int = 32, distributed: bool = True):
    """``KerasNet.predict`` (``Topology.scala:343`` family)."""
    if self.params is None:
        raise RuntimeError("no weights; fit() or init_weights() first")
    if self._compiled is None:
        self._loop = TrainingLoop(self, optax.identity(), objectives.get_loss("mse"), [])
    return self._loop.predict(x, batch_size=batch_size)


def _predict_classes(self: KerasNet, x, batch_size: int = 32, zero_based: bool = True):
    """``predictClass`` (``Predictor.scala:210``)."""
    from ....utils.prediction import probs_to_classes
    probs = self.predict(x, batch_size=batch_size)
    return probs_to_classes(probs, zero_based=zero_based)


# state attributes
KerasNet.params = None
KerasNet.net_state = None
KerasNet.opt_state = None
KerasNet.finished_epochs = 0
KerasNet.finished_iterations = 0
KerasNet._loop = None
KerasNet._checkpoint = None
KerasNet._train_summary = None
KerasNet._val_summary = None
KerasNet._lr = None

KerasNet.compile = _compile
KerasNet.init_weights = _init_weights
KerasNet.set_checkpoint = _set_checkpoint
KerasNet.set_tensorboard = _set_tensorboard
KerasNet.set_profile = _set_profile
KerasNet.get_train_summary = _get_train_summary
KerasNet.get_validation_summary = _get_validation_summary
KerasNet.fit = _fit
KerasNet.evaluate = _evaluate
KerasNet.predict = _predict
KerasNet.predict_classes = _predict_classes
