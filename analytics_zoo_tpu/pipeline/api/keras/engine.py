"""Graph/layer engine — the TPU-native equivalent of the reference's Keras-1
style API (``pipeline/api/keras/models/Topology.scala``) and its autograd
graph (``pipeline/api/autograd/math.scala``).

Design (idiomatic JAX, not a port):

* A ``Layer`` is a *functional* module: ``build(rng, input_shape) -> params``
  (a pytree) and ``call(params, x) -> y``. Stateful layers (BatchNorm)
  additionally carry a non-trainable ``state`` pytree threaded functionally
  through ``apply`` — no mutation, so everything jits/vmaps/shards cleanly.
* Output shapes are inferred with ``jax.eval_shape`` instead of per-layer
  ``computeOutputShape`` code (the reference implements shape inference by
  hand per layer).
* The functional-API ``Variable`` (operator overloading: ``+ - * / **`` and
  the ``AutoGrad`` op set of ``math.scala:32-365``) and Keras graph nodes are
  one graph system; ``Model(input, output)`` topologically evaluates it.
* ``Sequential`` and ``Model`` are themselves Layers, so they nest, mirroring
  ``KerasNet`` in ``Topology.scala:63``.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

# --------------------------------------------------------------------------
# dtype policy
# --------------------------------------------------------------------------

_compute_dtype = jnp.float32
_param_dtype = jnp.float32
#: who last set the policy: "default" | "direct" (user set_policy) |
#: "context" (init_zoo_context's zoo.compute.dtype). The context only
#: overrides policies IT owns — see init_zoo_context.
_policy_owner = "default"


def set_policy(compute_dtype: Any = jnp.float32, param_dtype: Any = jnp.float32):
    """Set the global mixed-precision policy. ``bfloat16`` compute keeps the
    MXU fed at full rate; params stay float32 for stable updates.

    A direct call takes OWNERSHIP of the policy: later context inits that
    don't name ``zoo.compute.dtype`` leave it alone (see
    ``common.context.init_zoo_context``)."""
    global _compute_dtype, _param_dtype, _policy_owner
    _compute_dtype = jnp.dtype(compute_dtype)
    _param_dtype = jnp.dtype(param_dtype)
    _policy_owner = "direct"


def policy_owner() -> str:
    return _policy_owner


def _set_policy_from_context(compute_dtype: Any):
    """Context-owned policy write (init_zoo_context only)."""
    global _policy_owner
    set_policy(compute_dtype=compute_dtype)
    _policy_owner = "context"


def _reset_policy():
    """Back to the pristine float32 default (reset_zoo_context only)."""
    global _policy_owner
    set_policy()
    _policy_owner = "default"


def compute_dtype():
    return _compute_dtype


def param_dtype():
    return _param_dtype


# --------------------------------------------------------------------------
# naming
# --------------------------------------------------------------------------

_uid_counters: Dict[str, int] = collections.defaultdict(int)


#: True while a layer/model shape-inference probe (``output_shape_for``'s
#: ``eval_shape``) is running — probes use placeholder batch dims, so
#: batch-dependent routing decisions (e.g. the seq-mesh divisibility check)
#: must not warn or raise strict-mode errors off them.
_in_shape_probe = False


def in_shape_probe() -> bool:
    return _in_shape_probe


def unique_name(prefix: str) -> str:
    _uid_counters[prefix] += 1
    return f"{prefix}{_uid_counters[prefix]}"


def reset_uids() -> None:
    _uid_counters.clear()


# --------------------------------------------------------------------------
# initializers (Keras-1 ``init=`` strings, e.g. Dense.scala / NeuralCF.scala)
# --------------------------------------------------------------------------

def get_initializer(name: Union[str, Callable]) -> Callable:
    """Map Keras-1 init names to jax.nn.initializers."""
    if callable(name):
        return name
    from jax.nn import initializers as I

    table = {
        "glorot_uniform": I.glorot_uniform(),
        "glorot_normal": I.glorot_normal(),
        "xavier": I.glorot_uniform(),
        "he_normal": I.he_normal(),
        "he_uniform": I.he_uniform(),
        "lecun_uniform": I.lecun_uniform(),
        "lecun_normal": I.lecun_normal(),
        "uniform": I.uniform(scale=0.05),
        "normal": I.normal(stddev=0.05),
        "zero": I.zeros,
        "zeros": I.zeros,
        "one": I.ones,
        "ones": I.ones,
        "orthogonal": I.orthogonal(),
    }
    if name not in table:
        raise ValueError(f"unknown initializer: {name}")
    return table[name]


# --------------------------------------------------------------------------
# layer-call interception (calibration / quantized execution)
# --------------------------------------------------------------------------

_LAYER_HOOK = contextvars.ContextVar("zoo_layer_hook", default=None)


@contextlib.contextmanager
def intercept_layer_calls(hook):
    """Scope a hook over every container-dispatched layer call.

    ``hook(layer, params, state, x, training, rng)`` returns ``(y, state)``
    to substitute the call, or ``None`` to run the layer normally. Used by
    the inference runtime for int8 activation calibration (record input
    ranges eagerly) and quantized execution (swap in ``quantized_call`` at
    trace time), by the fused LM-head loss (head → identity), by the
    sharded embedding engine (plain ``Embedding`` → row-partitioned
    dedup'd lookup, ``keras/sharded_embed.py``) and by the
    pipeline-parallel step builder (block run → ``gpipe_apply``);
    sub-layers invoked *inside* wrapper layers (TimeDistributed,
    Bidirectional) are not dispatched through containers and stay float.

    Nested scopes CHAIN: the innermost hook is consulted first and a
    ``None`` return falls through to the enclosing one — so the
    fused-loss head intercept composes with the training loop's pipeline
    intercept instead of silently replacing it. Entering a scope with
    ``hook=None`` keeps the historical meaning — interception DISABLED
    for the scope (the int8 runtime's ``qhook if act_scales else None``
    idiom), not a crash and not a chain link."""
    prev = _LAYER_HOOK.get()
    if prev is not None and hook is not None:
        inner = hook

        def hook(layer, params, state, x, training, rng):
            out = inner(layer, params, state, x, training, rng)
            if out is not None:
                return out
            return prev(layer, params, state, x, training, rng)
    token = _LAYER_HOOK.set(hook)
    try:
        yield
    finally:
        _LAYER_HOOK.reset(token)


def dispatch_layer(layer, params, state, x, *, training=False, rng=None):
    hook = _LAYER_HOOK.get()
    if hook is not None:
        out = hook(layer, params, state, x, training, rng)
        if out is not None:
            return out
    return layer.apply(params, state, x, training=training, rng=rng)


# --------------------------------------------------------------------------
# Layer base
# --------------------------------------------------------------------------

class Layer:
    """Base layer.

    Subclasses implement:

    * ``build(self, rng, input_shape) -> params`` — create trainable params.
      ``input_shape`` is a tuple (or list of tuples for multi-input layers)
      *including* a leading batch dim of ``None``.
    * ``call(self, params, x, *, training=False, rng=None) -> y``.

    Stateful layers instead override ``initial_state`` and ``apply``.
    """

    def __init__(self, name: Optional[str] = None, input_shape: Optional[Tuple] = None):
        # _auto_name marks names eligible for deterministic renaming when the
        # layer joins a container — cross-instance checkpoint/weight files
        # must not depend on process-global uid counters
        self._auto_name = name is None
        self.name = name or unique_name(type(self).__name__.lower() + "_")
        # Keras-1 convention: user-facing input_shape excludes the batch dim
        # (``KerasLayer.inputShape``); internally we carry (None, *dims).
        self._declared_input_shape = (
            (None,) + tuple(input_shape) if input_shape is not None else None
        )

    # ---- to be overridden -------------------------------------------------
    def build(self, rng: jax.Array, input_shape) -> Dict[str, Any]:
        return {}

    def initial_state(self, input_shape) -> Dict[str, Any]:
        return {}

    def call(self, params, x, *, training: bool = False, rng: Optional[jax.Array] = None):
        raise NotImplementedError(type(self).__name__)

    def apply(self, params, state, x, *, training: bool = False,
              rng: Optional[jax.Array] = None):
        """Returns ``(y, new_state)``. Default: stateless passthrough."""
        return self.call(params, x, training=training, rng=rng), state

    def get_config(self) -> Dict[str, Any]:
        return {}

    # ---- tensor-parallel sharding rules (SURVEY §2.4 TP — greenfield) -----
    def param_sharding(self, params):
        """PartitionSpec tree matching this layer's ``params``; ``None``
        leaves mean replicated. Layers whose weights shard over the ``model``
        mesh axis (Dense, Embedding) override this; everything else stays
        replicated — GSPMD propagates activation shardings from here."""
        return jax.tree.map(lambda _: None, params)

    # ---- shape inference --------------------------------------------------
    def output_shape_for(self, params, state, input_shape):
        """Infer output shape via abstract evaluation (no FLOPs)."""
        global _in_shape_probe
        spec = _shapes_to_specs(input_shape)
        rng = jax.random.key(0)
        prev = _in_shape_probe
        _in_shape_probe = True
        try:
            out = jax.eval_shape(
                lambda p, s, x: self.apply(p, s, x, training=False,
                                           rng=rng)[0],
                params, state, spec,
            )
        finally:
            _in_shape_probe = prev
        return jax.tree.map(lambda o: _spec_to_shape(o), out,
                            is_leaf=lambda o: isinstance(o, jax.ShapeDtypeStruct))

    # ---- graph building ---------------------------------------------------
    def __call__(self, x: Union["Variable", Sequence["Variable"]]) -> "Variable":
        """Functional-API call: connect this layer into the graph."""
        if isinstance(x, (list, tuple)):
            parents = [v.node for v in x]
        else:
            parents = [x.node]
        node = Node(self, parents)
        return Variable(node)

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


def _shapes_to_specs(input_shape, dtype=None):
    dtype = dtype or _compute_dtype
    if isinstance(input_shape, list):
        return [jax.ShapeDtypeStruct(_concrete(s), dtype) for s in input_shape]
    return jax.ShapeDtypeStruct(_concrete(input_shape), dtype)


def _concrete(shape):
    return tuple(1 if d is None else d for d in shape)


def _spec_to_shape(spec):
    # restore the symbolic batch dim
    return (None,) + tuple(spec.shape[1:])


# --------------------------------------------------------------------------
# Graph nodes & Variables (autograd)
# --------------------------------------------------------------------------

class Node:
    __slots__ = ("layer", "parents", "name")

    def __init__(self, layer: Layer, parents: List["Node"]):
        self.layer = layer
        self.parents = parents
        self.name = layer.name


class InputLayer(Layer):
    def __init__(self, shape: Tuple, name: Optional[str] = None):
        super().__init__(name=name or unique_name("input_"))
        self.shape = (None,) + tuple(shape)

    def call(self, params, x, *, training=False, rng=None):
        return x


class Lambda(Layer):
    """Arbitrary jnp-function layer — equivalent of the reference's
    ``autograd.Lambda`` (``pipeline/api/autograd/Lambda.scala``). ``fn`` takes
    the input (or list of inputs) and returns an array."""

    def __init__(self, fn: Callable, name: Optional[str] = None):
        super().__init__(name=name or unique_name("lambda_"))
        self.fn = fn

    def call(self, params, x, *, training=False, rng=None):
        if isinstance(x, (list, tuple)):
            return self.fn(*x)
        return self.fn(x)


class Variable:
    """Graph-node handle with operator overloading — parity with
    ``autograd.Variable`` (``autograd/math.scala:365-640``)."""

    def __init__(self, node: Node):
        self.node = node

    @property
    def name(self) -> str:
        return self.node.name

    # -- binary ops ---------------------------------------------------------
    def _binop(self, other, fn, opname):
        if isinstance(other, Variable):
            return Lambda(fn, name=unique_name(opname + "_"))([self, other])
        return Lambda(lambda a: fn(a, other), name=unique_name(opname + "_"))(self)

    def _rbinop(self, other, fn, opname):
        return Lambda(lambda a: fn(other, a), name=unique_name(opname + "_"))(self)

    def __add__(self, o): return self._binop(o, jnp.add, "add")
    def __radd__(self, o): return self._rbinop(o, jnp.add, "add")
    def __sub__(self, o): return self._binop(o, jnp.subtract, "sub")
    def __rsub__(self, o): return self._rbinop(o, jnp.subtract, "sub")
    def __mul__(self, o): return self._binop(o, jnp.multiply, "mul")
    def __rmul__(self, o): return self._rbinop(o, jnp.multiply, "mul")
    def __truediv__(self, o): return self._binop(o, jnp.divide, "div")
    def __rtruediv__(self, o): return self._rbinop(o, jnp.divide, "div")
    def __pow__(self, o): return self._binop(o, jnp.power, "pow")
    def __neg__(self): return Lambda(jnp.negative, name=unique_name("neg_"))(self)

    # -- keras-style slicing (Variable.slice / indexSelect in math.scala) ---
    def __getitem__(self, idx):
        return Lambda(lambda a: a[idx], name=unique_name("slice_"))(self)

    def slice(self, dim: int, start: int, length: int) -> "Variable":
        def f(a):
            sl = [slice(None)] * a.ndim
            sl[dim] = slice(start, start + length)
            return a[tuple(sl)]
        return Lambda(f, name=unique_name("slice_"))(self)

    def index_select(self, dim: int, index: int) -> "Variable":
        return Lambda(lambda a: jnp.take(a, index, axis=dim),
                      name=unique_name("indexselect_"))(self)

    def squeeze(self, dim: int) -> "Variable":
        return Lambda(lambda a: jnp.squeeze(a, axis=dim),
                      name=unique_name("squeeze_"))(self)


def Input(shape: Tuple, name: Optional[str] = None) -> Variable:
    """Create a graph input — ``autograd.Variable(inputShape)`` / keras
    ``Input`` in the reference."""
    layer = InputLayer(shape, name=name)
    node = Node(layer, [])
    return Variable(node)


# --------------------------------------------------------------------------
# Containers
# --------------------------------------------------------------------------

class KerasNet(Layer):
    """Base of ``Sequential``/``Model`` — the counterpart of the reference's
    abstract ``KerasNet`` (``Topology.scala:63-600``). Training methods
    (``compile/fit/evaluate/predict``) are attached in ``training.py`` to keep
    the graph engine free of the optimizer machinery."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(name=name)
        self._compiled = None  # set by .compile()

    # populated by subclasses
    def build(self, rng, input_shape):
        raise NotImplementedError

    # ---- convenience: materialize params for a given input shape ----------
    def init(self, rng: jax.Array, input_shape=None):
        """Returns ``(params, state)`` for this network."""
        shape = input_shape
        if shape is not None and not isinstance(shape, list):
            if shape and (shape[0] is not None and not isinstance(shape[0], (list, tuple))):
                # user passed shape without batch dim
                shape = (None,) + tuple(shape)
            else:
                shape = tuple(shape)
        params = self.build(rng, shape)
        state = self.initial_state(shape)
        return params, state


class Sequential(KerasNet):
    """Linear stack — parity with ``Sequential`` (``Topology.scala:825-959``)."""

    def __init__(self, layers: Optional[Sequence[Layer]] = None, name: Optional[str] = None):
        super().__init__(name=name or unique_name("sequential_"))
        self.layers: List[Layer] = []
        self._shapes: List[Any] = []  # per-layer input shapes, set at build
        for l in layers or []:
            self.add(l)

    def add(self, layer: Layer) -> "Sequential":
        if getattr(layer, "_auto_name", False):
            # deterministic position-based name: two identically-built models
            # (even in one process) produce identical param keys, so saved
            # weights/checkpoints restore by structure, not by uid counters
            taken = {l.name for l in self.layers}
            cand = f"{type(layer).__name__.lower()}_{len(self.layers)}"
            while cand in taken:  # dodge user-chosen names
                cand += "_"
            layer.name = cand
            layer._auto_name = False  # keep one name if the layer is reused
        self.layers.append(layer)
        return self

    @property
    def input_shape(self):
        for l in self.layers:
            if l._declared_input_shape is not None:
                return l._declared_input_shape
            if isinstance(l, InputLayer):
                return l.shape
        return None

    def build(self, rng, input_shape=None):
        shape = input_shape or self.input_shape
        if shape is None:
            raise ValueError(
                f"{self.name}: first layer needs input_shape=..., or pass one to init()")
        names = [l.name for l in self.layers]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(
                f"{self.name}: duplicate layer names {dupes} — params would "
                f"silently collide; give the layers distinct name=...")
        params: Dict[str, Any] = {}
        self._shapes = []
        keys = jax.random.split(rng, max(len(self.layers), 1))
        for k, layer in zip(keys, self.layers):
            self._shapes.append(shape)
            p = layer.build(k, shape)
            s = layer.initial_state(shape)
            params[layer.name] = p
            shape = layer.output_shape_for(p, s, shape)
        self._built_output_shape = shape
        return params

    def initial_state(self, input_shape=None):
        shape = input_shape or self.input_shape
        state: Dict[str, Any] = {}
        for layer, lshape in zip(self.layers, self._shapes):
            s = layer.initial_state(lshape)
            if s:
                state[layer.name] = s
        return state

    def apply(self, params, state, x, *, training=False, rng=None):
        new_state = dict(state) if state else {}
        h = x
        for i, layer in enumerate(self.layers):
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            lstate = state.get(layer.name, {}) if state else {}
            h, ns = dispatch_layer(layer, params.get(layer.name, {}), lstate,
                                   h, training=training, rng=lrng)
            if ns:
                new_state[layer.name] = ns
        return h, new_state

    def call(self, params, x, *, training=False, rng=None):
        y, _ = self.apply(params, {}, x, training=training, rng=rng)
        return y

    def param_sharding(self, params):
        return {l.name: l.param_sharding(params[l.name])
                for l in self.layers if l.name in params}


class Model(KerasNet):
    """Graph container — parity with ``Model`` (``Topology.scala:602``) and
    the autograd graph. ``Model(input=[vars], output=var)``."""

    def __init__(self, input, output, name: Optional[str] = None):
        super().__init__(name=name or unique_name("model_"))
        self.inputs: List[Variable] = list(input) if isinstance(input, (list, tuple)) else [input]
        self.outputs: List[Variable] = list(output) if isinstance(output, (list, tuple)) else [output]
        self._multi_output = isinstance(output, (list, tuple))
        self._topo = self._toposort()
        # deterministic topo-order names (see Sequential.add): identical
        # graphs get identical param keys regardless of uid-counter state
        taken = {n.layer.name for n in self._topo
                 if not getattr(n.layer, "_auto_name", False)}
        for i, node in enumerate(self._topo):
            if getattr(node.layer, "_auto_name", False):
                cand = f"{type(node.layer).__name__.lower()}_{i}"
                while cand in taken:  # dodge user-chosen names
                    cand += "_"
                node.layer.name = cand
                taken.add(cand)
                node.layer._auto_name = False  # shared layers keep one name
            node.name = node.layer.name

    def _toposort(self) -> List[Node]:
        seen: Dict[int, Node] = {}
        order: List[Node] = []

        def visit(node: Node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for p in node.parents:
                visit(p)
            order.append(node)

        for v in self.outputs:
            visit(v.node)
        return order

    @property
    def input_shape(self):
        shapes = [v.node.layer.shape for v in self.inputs]
        return shapes if len(shapes) > 1 else shapes[0]

    def build(self, rng, input_shape=None):
        by_name: Dict[str, int] = {}
        for n in self._topo:
            if n.parents:  # param-bearing nodes only
                prev = by_name.setdefault(n.name, id(n.layer))
                if prev != id(n.layer):  # same layer object = weight sharing, OK
                    raise ValueError(
                        f"{self.name}: two different layers named {n.name!r} — "
                        f"params would silently collide; give them distinct "
                        f"name=...")
        shapes = input_shape or self.input_shape
        if not isinstance(shapes, list):
            shapes = [shapes]
        shape_of: Dict[int, Any] = {}
        for v, s in zip(self.inputs, shapes):
            shape_of[id(v.node)] = s

        params: Dict[str, Any] = {}
        self._state_shapes: Dict[str, Any] = {}
        keys = jax.random.split(rng, max(len(self._topo), 1))
        for k, node in zip(keys, self._topo):
            if not node.parents:  # input node
                if id(node) not in shape_of:
                    shape_of[id(node)] = node.layer.shape
                continue
            pshapes = [shape_of[id(p)] for p in node.parents]
            in_shape = pshapes if len(pshapes) > 1 else pshapes[0]
            p = node.layer.build(k, in_shape)
            s = node.layer.initial_state(in_shape)
            params[node.name] = p
            self._state_shapes[node.name] = in_shape
            shape_of[id(node)] = node.layer.output_shape_for(p, s, in_shape)
        self._built_output_shape = [shape_of[id(v.node)] for v in self.outputs]
        return params

    def initial_state(self, input_shape=None):
        if not hasattr(self, "_state_shapes"):
            # build must run first to record shapes; tolerate state-only query
            raise RuntimeError("call build() before initial_state() on Model")
        state: Dict[str, Any] = {}
        for node in self._topo:
            if not node.parents:
                continue
            s = node.layer.initial_state(self._state_shapes[node.name])
            if s:
                state[node.name] = s
        return state

    def apply(self, params, state, x, *, training=False, rng=None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        if len(xs) != len(self.inputs):
            raise ValueError(
                f"{self.name} expects {len(self.inputs)} inputs, got {len(xs)}")
        value_of: Dict[int, Any] = {id(v.node): arr for v, arr in zip(self.inputs, xs)}
        new_state = dict(state) if state else {}
        for i, node in enumerate(self._topo):
            if not node.parents:
                continue
            args = [value_of[id(p)] for p in node.parents]
            arg = args if len(args) > 1 else args[0]
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            lstate = state.get(node.name, {}) if state else {}
            y, ns = dispatch_layer(node.layer, params.get(node.name, {}),
                                   lstate, arg, training=training, rng=lrng)
            if ns:
                new_state[node.name] = ns
            value_of[id(node)] = y
        outs = [value_of[id(v.node)] for v in self.outputs]
        out = outs if self._multi_output else outs[0]
        return out, new_state

    def call(self, params, x, *, training=False, rng=None):
        y, _ = self.apply(params, {}, x, training=training, rng=rng)
        return y

    def new_graph(self, outputs: Sequence[str]) -> "Model":
        """Sub-graph surgery: new Model ending at the named nodes — parity
        with ``GraphNet.newGraph(output)`` (``pipeline/api/net/NetUtils.scala``)."""
        by_name = {n.name: n for n in self._topo}
        outs = [Variable(by_name[o]) for o in outputs]
        return Model(self.inputs, outs if len(outs) > 1 else outs[0])

    def param_sharding(self, params):
        out = {}
        for n in self._topo:
            if n.name in params and n.name not in out:
                out[n.name] = n.layer.param_sharding(params[n.name])
        return out


def install_imported_weights(model: "KerasNet", weights, states=None,
                             source: str = "imported") -> "KerasNet":
    """Shared installer for model importers (caffe/torch/...): init the
    graph, then overwrite named layers' params (shape-checked) and running
    state. ``weights``/``states`` map layer name → leaf dict."""
    model.init_weights()
    for lname, w in weights.items():
        tmpl = model.params.get(lname)
        if tmpl is None:
            raise ValueError(f"{source} weights for unknown layer {lname!r}")
        for k, v in w.items():
            if k not in tmpl:
                raise ValueError(f"{lname}: {source} provides param {k!r}; "
                                 f"layer has {sorted(tmpl)}")
            if np.shape(tmpl[k]) != np.shape(v):
                raise ValueError(f"{lname}.{k}: {source} weight shape "
                                 f"{np.shape(v)} vs graph "
                                 f"{np.shape(tmpl[k])}")
        model.params[lname] = {k: jnp.asarray(v) for k, v in w.items()}  # zoolint: disable=ZL009 one-time load; per-layer shapes differ, nothing to batch
    for lname, s in (states or {}).items():
        model.net_state[lname] = {k: jnp.asarray(v) for k, v in s.items()}  # zoolint: disable=ZL009 one-time load; per-layer shapes differ
    return model
