from .engine import (Layer, Input, Variable, Lambda, InputLayer,  # noqa: F401
                     Sequential, Model, KerasNet, set_policy)
from . import training  # noqa: F401  (attaches compile/fit/evaluate/predict)
from . import objectives, metrics, optimizers  # noqa: F401
