"""Pooling layers — parity with the reference's Keras-1 pooling family
(``pipeline/api/keras/layers/``: MaxPooling1D/2D/3D.scala,
AveragePooling1D/2D/3D.scala, GlobalMaxPooling*.scala,
GlobalAveragePooling*.scala).

All channels-last; windows run through ``lax.reduce_window`` which XLA fuses
with neighbouring elementwise ops. Average pooling under ``same`` padding
divides by the true window population (edge windows are smaller), matching
Keras/TF semantics.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import lax

from ..engine import Layer
from ._shapes import triple as _triple


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _pool(x, init, op, window, strides, padding):
    dims = (1,) + tuple(window) + (1,)
    strd = (1,) + tuple(strides) + (1,)
    return lax.reduce_window(x, init, op, dims, strd, padding)


class MaxPooling1D(Layer):
    """``MaxPooling1D(pool_length, stride, border_mode)``."""

    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 border_mode: str = "valid", **kwargs):
        super().__init__(**kwargs)
        self.pool_length = pool_length
        self.stride = stride if stride is not None else pool_length
        self.border_mode = border_mode.upper()

    def call(self, params, x, *, training=False, rng=None):
        return _pool(x, -jnp.inf, lax.max, (self.pool_length,),
                     (self.stride,), self.border_mode)


class AveragePooling1D(Layer):
    """``AveragePooling1D(pool_length, stride, border_mode)``."""

    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 border_mode: str = "valid", **kwargs):
        super().__init__(**kwargs)
        self.pool_length = pool_length
        self.stride = stride if stride is not None else pool_length
        self.border_mode = border_mode.upper()

    def call(self, params, x, *, training=False, rng=None):
        s = _pool(x.astype(jnp.float32), 0.0, lax.add, (self.pool_length,),
                  (self.stride,), self.border_mode)
        n = _pool(jnp.ones_like(x, jnp.float32), 0.0, lax.add,
                  (self.pool_length,), (self.stride,), self.border_mode)
        return (s / n).astype(x.dtype)


class MaxPooling2D(Layer):
    """``MaxPooling2D(pool_size, strides, border_mode)`` — channels-last."""

    def __init__(self, pool_size: Tuple[int, int] = (2, 2),
                 strides: Optional[Tuple[int, int]] = None,
                 border_mode: str = "valid", **kwargs):
        super().__init__(**kwargs)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.border_mode = border_mode.upper()

    def call(self, params, x, *, training=False, rng=None):
        return _pool(x, -jnp.inf, lax.max, self.pool_size, self.strides,
                     self.border_mode)


class AveragePooling2D(Layer):
    """``AveragePooling2D(pool_size, strides, border_mode)``."""

    def __init__(self, pool_size: Tuple[int, int] = (2, 2),
                 strides: Optional[Tuple[int, int]] = None,
                 border_mode: str = "valid", **kwargs):
        super().__init__(**kwargs)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.border_mode = border_mode.upper()

    def call(self, params, x, *, training=False, rng=None):
        s = _pool(x.astype(jnp.float32), 0.0, lax.add, self.pool_size,
                  self.strides, self.border_mode)
        n = _pool(jnp.ones_like(x, jnp.float32), 0.0, lax.add, self.pool_size,
                  self.strides, self.border_mode)
        return (s / n).astype(x.dtype)


class GlobalMaxPooling1D(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.max(x, axis=1)


class GlobalAveragePooling1D(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.mean(x, axis=1)


class GlobalMaxPooling2D(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.max(x, axis=(1, 2))


class GlobalAveragePooling2D(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.mean(x, axis=(1, 2))


class MaxPooling3D(Layer):
    """``MaxPooling3D(pool_size, strides, border_mode)`` — (B, D, H, W, C)."""

    def __init__(self, pool_size: Tuple[int, int, int] = (2, 2, 2),
                 strides: Optional[Tuple[int, int, int]] = None,
                 border_mode: str = "valid", **kwargs):
        super().__init__(**kwargs)
        self.pool_size = _triple(pool_size)
        self.strides = (_triple(strides) if strides is not None
                        else self.pool_size)
        self.border_mode = border_mode.upper()

    def call(self, params, x, *, training=False, rng=None):
        return _pool(x, -jnp.inf, lax.max, self.pool_size, self.strides,
                     self.border_mode)


class AveragePooling3D(Layer):
    """``AveragePooling3D(pool_size, strides, border_mode)``."""

    def __init__(self, pool_size: Tuple[int, int, int] = (2, 2, 2),
                 strides: Optional[Tuple[int, int, int]] = None,
                 border_mode: str = "valid", **kwargs):
        super().__init__(**kwargs)
        self.pool_size = _triple(pool_size)
        self.strides = (_triple(strides) if strides is not None
                        else self.pool_size)
        self.border_mode = border_mode.upper()

    def call(self, params, x, *, training=False, rng=None):
        s = _pool(x.astype(jnp.float32), 0.0, lax.add, self.pool_size,
                  self.strides, self.border_mode)
        n = _pool(jnp.ones_like(x, jnp.float32), 0.0, lax.add,
                  self.pool_size, self.strides, self.border_mode)
        return (s / n).astype(x.dtype)


class GlobalMaxPooling3D(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.max(x, axis=(1, 2, 3))


class GlobalAveragePooling3D(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.mean(x, axis=(1, 2, 3))


class KMaxPooling(Layer):
    """``KMaxPooling(k, dim)`` (``KMaxPooling.scala``) — keep the k largest
    values along ``dim`` (default: the time axis 1) in their ORIGINAL
    order (top-k by value, then index-sort — the order-preserving contract
    of the reference/caffe form). Input (B, T, C) → (B, k, C)."""

    def __init__(self, k: int, dim: int = 1, **kwargs):
        super().__init__(**kwargs)
        if k < 1:
            raise ValueError(f"KMaxPooling needs k >= 1, got {k}")
        self.k = int(k)
        self.dim = int(dim)

    def call(self, params, x, *, training=False, rng=None):
        axis = self.dim % x.ndim
        if x.shape[axis] < self.k:
            raise ValueError(f"KMaxPooling k={self.k} exceeds dim size "
                             f"{x.shape[axis]}")
        moved = jnp.moveaxis(x, axis, -1)
        _, idx = lax.top_k(moved, self.k)           # by value, desc
        idx = jnp.sort(idx, axis=-1)                # restore original order
        out = jnp.take_along_axis(moved, idx, axis=-1)
        return jnp.moveaxis(out, -1, axis)
