"""3D convolution family + the structured-conv extras — parity with the
reference's ``keras/layers/{Convolution3D,ConvLSTM2D,ZeroPadding3D,
Cropping3D,UpSampling3D,SpatialDropout1D/2D/3D,LocallyConnected2D,
ShareConvolution2D,MaxoutDense,LRN2D}.scala``.

All channels-last (the reference's NCDHW maps to NDHWC on TPU: depth/height/
width become spatial dims of one ``conv_general_dilated``, which XLA tiles
onto the MXU like any conv).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..engine import Layer, compute_dtype, get_initializer, param_dtype
from ._shapes import triple as _triple
from .core import get_activation


def _padding3(mode: str):
    return mode.upper() if isinstance(mode, str) else mode


class Convolution3D(Layer):
    """``Convolution3D(nb_filter, kernel_dim1..3)`` — input (B, D, H, W, C)."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, init: str = "glorot_uniform",
                 activation=None, border_mode: str = "valid",
                 subsample: Tuple[int, int, int] = (1, 1, 1),
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.init = init
        self.activation = get_activation(activation)
        self.border_mode = border_mode
        self.subsample = _triple(subsample)
        self.bias = bias

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        p = {"W": get_initializer(self.init)(
            rng, self.kernel + (in_ch, self.nb_filter), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = lax.conv_general_dilated(
            x.astype(cd), params["W"].astype(cd),
            window_strides=self.subsample,
            padding=_padding3(self.border_mode),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y


class ZeroPadding3D(Layer):
    def __init__(self, padding: Tuple[int, int, int] = (1, 1, 1), **kwargs):
        super().__init__(**kwargs)
        self.padding = _triple(padding)

    def call(self, params, x, *, training=False, rng=None):
        p = self.padding
        return jnp.pad(x, ((0, 0), (p[0], p[0]), (p[1], p[1]),
                           (p[2], p[2]), (0, 0)))


class Cropping3D(Layer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), **kwargs):
        super().__init__(**kwargs)
        self.cropping = tuple((int(a), int(b)) for a, b in cropping)

    def call(self, params, x, *, training=False, rng=None):
        (d0, d1), (h0, h1), (w0, w1) = self.cropping
        return x[:, d0:x.shape[1] - d1, h0:x.shape[2] - h1,
                 w0:x.shape[3] - w1, :]


class UpSampling3D(Layer):
    def __init__(self, size: Tuple[int, int, int] = (2, 2, 2), **kwargs):
        super().__init__(**kwargs)
        self.size = _triple(size)

    def call(self, params, x, *, training=False, rng=None):
        for axis, r in zip((1, 2, 3), self.size):
            x = jnp.repeat(x, r, axis=axis)
        return x


class _SpatialDropoutBase(Layer):
    """Drop whole channels: the mask broadcasts over all spatial dims."""
    ndim_spatial = 1

    def __init__(self, p: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.p = float(p)

    def call(self, params, x, *, training=False, rng=None):
        if not training or rng is None or self.p <= 0.0:
            return x
        mask_shape = (x.shape[0],) + (1,) * self.ndim_spatial + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, 1.0 - self.p, mask_shape)
        return jnp.where(keep, x / (1.0 - self.p), jnp.zeros_like(x))


class SpatialDropout1D(_SpatialDropoutBase):
    ndim_spatial = 1


class SpatialDropout2D(_SpatialDropoutBase):
    ndim_spatial = 2


class SpatialDropout3D(_SpatialDropoutBase):
    ndim_spatial = 3


class ConvLSTM2D(Layer):
    """``ConvLSTM2D(nb_filter, nb_kernel)`` — LSTM whose gates are 'same'
    2D convs. Input (B, T, H, W, C) → (B, H, W, F) or the full sequence
    (B, T, H, W, F) with ``return_sequences``. The time loop is a
    ``lax.scan`` (one compiled step body, like the package's LSTM/GRU)."""

    def __init__(self, nb_filter: int, nb_kernel: int,
                 init: str = "glorot_uniform",
                 inner_activation="hard_sigmoid", activation="tanh",
                 return_sequences: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_kernel = int(nb_kernel)
        self.init = init
        self.inner_activation = get_activation(inner_activation)
        self.activation = get_activation(activation)
        self.return_sequences = return_sequences

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        k = self.nb_kernel
        kx, kh = jax.random.split(rng)
        return {
            "Wx": get_initializer(self.init)(
                kx, (k, k, in_ch, 4 * self.nb_filter), param_dtype()),
            "Wh": get_initializer(self.init)(
                kh, (k, k, self.nb_filter, 4 * self.nb_filter), param_dtype()),
            "b": jnp.zeros((4 * self.nb_filter,), param_dtype()),
        }

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        b, t, h, w, _ = x.shape
        f = self.nb_filter

        def conv(inp, kern):
            return lax.conv_general_dilated(
                inp, kern, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32).astype(cd)

        wx = params["Wx"].astype(cd)
        wh = params["Wh"].astype(cd)
        bias = params["b"].astype(cd)

        def step(carry, x_t):
            h_prev, c_prev = carry
            z = conv(x_t, wx) + conv(h_prev, wh) + bias
            i, fgate, g, o = jnp.split(z, 4, axis=-1)
            i = self.inner_activation(i)
            fgate = self.inner_activation(fgate)
            o = self.inner_activation(o)
            c = fgate * c_prev + i * self.activation(g)
            h_new = o * self.activation(c)
            return (h_new, c), h_new

        h0 = jnp.zeros((b, h, w, f), cd)
        xs = jnp.moveaxis(x.astype(cd), 1, 0)          # (T, B, H, W, C)
        (h_last, _), hs = lax.scan(step, (h0, h0), xs)
        if self.return_sequences:
            return jnp.moveaxis(hs, 0, 1)              # (B, T, H, W, F)
        return h_last


class LocallyConnected2D(Layer):
    """``LocallyConnected2D.scala`` — conv with UNSHARED weights per output
    position: patches are extracted once, then one einsum against the
    (H'·W', k·k·C, F) weight tensor (a single batched MXU contraction)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample: Tuple[int, int] = (1, 1),
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_row, self.nb_col = int(nb_row), int(nb_col)
        self.activation = get_activation(activation)
        self.subsample = (int(subsample[0]), int(subsample[1])) \
            if isinstance(subsample, (tuple, list)) else (int(subsample),) * 2
        self.bias = bias

    def _out_hw(self, h, w):
        oh = (h - self.nb_row) // self.subsample[0] + 1
        ow = (w - self.nb_col) // self.subsample[1] + 1
        return oh, ow

    def build(self, rng, input_shape):
        _, h, w, c = input_shape
        oh, ow = self._out_hw(h, w)
        patch = self.nb_row * self.nb_col * c
        p = {"W": get_initializer("glorot_uniform")(
            rng, (oh * ow, patch, self.nb_filter), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((oh, ow, self.nb_filter), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        _, h, w, c = x.shape
        oh, ow = self._out_hw(h, w)
        patches = lax.conv_general_dilated_patches(
            x.astype(cd), (self.nb_row, self.nb_col), self.subsample,
            "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # conv_general_dilated_patches yields features ordered (C, kh, kw);
        # reorder to (kh, kw, C) to match the W layout
        patches = patches.reshape(x.shape[0], oh, ow, c,
                                  self.nb_row * self.nb_col)
        patches = jnp.moveaxis(patches, 3, -1)
        patches = patches.reshape(x.shape[0], oh * ow, -1)
        y = jnp.einsum("bpk,pkf->bpf", patches, params["W"].astype(cd),
                       preferred_element_type=jnp.float32).astype(cd)
        y = y.reshape(x.shape[0], oh, ow, self.nb_filter)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y


class MaxoutDense(Layer):
    """``MaxoutDense(output_dim, nb_feature)`` — max over nb_feature linear
    pieces."""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.output_dim = int(output_dim)
        self.nb_feature = int(nb_feature)
        self.bias = bias

    def build(self, rng, input_shape):
        in_dim = input_shape[-1]
        p = {"W": get_initializer("glorot_uniform")(
            rng, (in_dim, self.nb_feature * self.output_dim), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_feature * self.output_dim,),
                               param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = jnp.matmul(x.astype(cd), params["W"].astype(cd),
                       preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        y = y.reshape(x.shape[:-1] + (self.nb_feature, self.output_dim))
        return jnp.max(y, axis=-2)


class LRN2D(Layer):
    """``LRN2D(alpha, k, beta, n)`` — cross-channel local response norm:
    x / (k + alpha/n * sum_{window n} x^2) ** beta (channels-last)."""

    def __init__(self, alpha: float = 1e-4, k: float = 1.0,
                 beta: float = 0.75, n: int = 5, **kwargs):
        super().__init__(**kwargs)
        self.alpha, self.k, self.beta, self.n = (float(alpha), float(k),
                                                 float(beta), int(n))

    def call(self, params, x, *, training=False, rng=None):
        half = self.n // 2
        sq = jnp.square(x.astype(jnp.float32))
        # sliding channel-window sum via padded cumulative trick
        pad = jnp.pad(sq, ((0, 0),) * (x.ndim - 1) + ((half, half),))
        win = sum(lax.slice_in_dim(pad, i, i + x.shape[-1], axis=-1)
                  for i in range(self.n))
        denom = jnp.power(self.k + self.alpha / self.n * win, self.beta)
        return (x.astype(jnp.float32) / denom).astype(x.dtype)


class ConvLSTM3D(Layer):
    """``ConvLSTM3D(nb_filter, nb_kernel)`` (``ConvLSTM3D.scala``) — LSTM
    whose gates are 'same' 3D convs. Input (B, T, D, H, W, C) →
    (B, D, H, W, F), or the full sequence with ``return_sequences`` — the
    volumetric sibling of :class:`ConvLSTM2D`, same ``lax.scan`` time
    loop."""

    def __init__(self, nb_filter: int, nb_kernel: int,
                 init: str = "glorot_uniform",
                 inner_activation="hard_sigmoid", activation="tanh",
                 return_sequences: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_kernel = int(nb_kernel)
        self.init = init
        self.inner_activation = get_activation(inner_activation)
        self.activation = get_activation(activation)
        self.return_sequences = return_sequences

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        k = self.nb_kernel
        kx, kh = jax.random.split(rng)
        return {
            "Wx": get_initializer(self.init)(
                kx, (k, k, k, in_ch, 4 * self.nb_filter), param_dtype()),
            "Wh": get_initializer(self.init)(
                kh, (k, k, k, self.nb_filter, 4 * self.nb_filter),
                param_dtype()),
            "b": jnp.zeros((4 * self.nb_filter,), param_dtype()),
        }

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        b, t, d, h, w, _ = x.shape
        f = self.nb_filter

        def conv(inp, kern):
            return lax.conv_general_dilated(
                inp, kern, (1, 1, 1), "SAME",
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
                preferred_element_type=jnp.float32).astype(cd)

        wx = params["Wx"].astype(cd)
        wh = params["Wh"].astype(cd)
        bias = params["b"].astype(cd)

        def step(carry, x_t):
            h_prev, c_prev = carry
            z = conv(x_t, wx) + conv(h_prev, wh) + bias
            i, fgate, g, o = jnp.split(z, 4, axis=-1)
            i = self.inner_activation(i)
            fgate = self.inner_activation(fgate)
            o = self.inner_activation(o)
            c = fgate * c_prev + i * self.activation(g)
            h_new = o * self.activation(c)
            return (h_new, c), h_new

        h0 = jnp.zeros((b, d, h, w, f), cd)
        xs = jnp.moveaxis(x.astype(cd), 1, 0)       # (T, B, D, H, W, C)
        (h_last, _), hs = lax.scan(step, (h0, h0), xs)
        if self.return_sequences:
            return jnp.moveaxis(hs, 0, 1)           # (B, T, D, H, W, F)
        return h_last


class WithinChannelLRN(Layer):
    """``WithinChannelLRN2D.scala`` (caffe's WITHIN_CHANNEL LRN) — local
    response normalization over a ``size`` x ``size`` SPATIAL window inside
    each channel: x / (1 + alpha * avg_window(x^2)) ** beta. One avg-pool of
    x² (SAME padding), so XLA fuses it like any pooling op."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, **kwargs):
        super().__init__(**kwargs)
        self.size, self.alpha, self.beta = int(size), float(alpha), float(beta)

    def call(self, params, x, *, training=False, rng=None):
        sq = jnp.square(x.astype(jnp.float32))
        win = lax.reduce_window(
            sq, 0.0, lax.add, (1, self.size, self.size, 1), (1, 1, 1, 1),
            "SAME")
        avg = win / float(self.size * self.size)
        denom = jnp.power(1.0 + self.alpha * avg, self.beta)
        return (x.astype(jnp.float32) / denom).astype(x.dtype)
