from ..engine import Input, InputLayer, Lambda  # noqa: F401
from .core import (Activation, Dense, Dropout, Flatten, Reshape, Permute,  # noqa: F401
                   RepeatVector, Merge, merge, Select, Squeeze, ExpandDim,
                   Narrow, Masking, GaussianNoise, GaussianDropout,
                   TimeDistributed, Highway, SparseDense, get_activation)
from .embeddings import (Embedding, ShardedEmbedding, SparseEmbedding,  # noqa: F401
                         WordEmbedding)
from .normalization import BatchNormalization, LayerNorm, L2Normalize  # noqa: F401
from .convolution import (AtrousConvolution1D, AtrousConvolution2D,  # noqa: F401
                          Convolution1D, Convolution2D, Cropping1D,
                          Cropping2D, Deconvolution2D,
                          DepthwiseConvolution2D, LocallyConnected1D,
                          SeparableConvolution1D,
                          SeparableConvolution2D, ShareConvolution2D,
                          UpSampling1D, UpSampling2D,
                          ZeroPadding1D, ZeroPadding2D)
from .convolution3d import (ConvLSTM2D, ConvLSTM3D, Convolution3D,  # noqa: F401
                            Cropping3D, LRN2D, LocallyConnected2D,
                            MaxoutDense, SpatialDropout1D, SpatialDropout2D,
                            SpatialDropout3D, UpSampling3D,
                            WithinChannelLRN, ZeroPadding3D)
from .pooling import (AveragePooling1D, AveragePooling2D, AveragePooling3D,  # noqa: F401
                      GlobalAveragePooling1D, GlobalAveragePooling2D,
                      GlobalAveragePooling3D, GlobalMaxPooling1D,
                      GlobalMaxPooling2D, GlobalMaxPooling3D, KMaxPooling,
                      MaxPooling1D, MaxPooling2D, MaxPooling3D)
from .advanced_activations import (ELU, BinaryThreshold, HardShrink,  # noqa: F401
                                   HardTanh, LeakyReLU, PReLU, RReLU, SReLU,
                                   SoftShrink, Softmax, Threshold,
                                   ThresholdedReLU)
from .elementwise import (AddConstant, CAdd, CMul, Exp, Expand,  # noqa: F401
                          GaussianSampler, Log, Max, Mul, MulConstant,
                          Negative, Power, ResizeBilinear, Scale, Sqrt,
                          Square)
from .gpipe import GPipe, Pipeline  # noqa: F401
from .moe import SparseMoE  # noqa: F401
from .recurrent import GRU, LSTM, Bidirectional, SimpleRNN  # noqa: F401
from .self_attention import (BERT, MultiHeadSelfAttention,  # noqa: F401
                             TransformerBlock, TransformerLayer)
