from ..engine import Input, InputLayer, Lambda  # noqa: F401
from .core import (Activation, Dense, Dropout, Flatten, Reshape, Permute,  # noqa: F401
                   RepeatVector, Merge, merge, Select, Squeeze, ExpandDim,
                   Narrow, Masking, GaussianNoise, GaussianDropout,
                   TimeDistributed, Highway, SparseDense, get_activation)
from .embeddings import Embedding, SparseEmbedding, WordEmbedding  # noqa: F401
from .normalization import BatchNormalization, LayerNorm, L2Normalize  # noqa: F401
