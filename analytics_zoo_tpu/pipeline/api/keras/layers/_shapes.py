"""Shared kernel/stride tuple normalizers for the layer modules."""

from __future__ import annotations

from typing import Tuple


def pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        if len(v) != 2:
            raise ValueError(f"expected 2 values, got {v!r}")
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        if len(v) != 3:
            raise ValueError(f"expected 3 values, got {v!r}")
        return tuple(int(a) for a in v)
    return (int(v),) * 3
