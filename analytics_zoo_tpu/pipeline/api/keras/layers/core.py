"""Core layers — parity with the reference's Keras-1 core layer set
(``pipeline/api/keras/layers/``: Dense.scala, Dropout.scala, Flatten.scala,
Merge.scala, Reshape.scala, Permute.scala, RepeatVector.scala, ...), built as
functional JAX modules so XLA fuses the elementwise chains into the matmuls.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ..engine import (
    Layer, compute_dtype, get_initializer, param_dtype, unique_name,
)

# --------------------------------------------------------------------------
# activations (keras/layers/Activation.scala registry)
# --------------------------------------------------------------------------

ACTIVATIONS: Dict[str, Callable] = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    # Keras-1/BigDL hard_sigmoid is clip(0.2x+0.5, 0, 1) — NOT jax.nn's
    # relu6(x+3)/6 variant; the reference's RNN defaults depend on this
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "linear": lambda x: x,
    "exp": jnp.exp,
}


def get_activation(act: Union[str, Callable, None]) -> Optional[Callable]:
    if act is None or callable(act):
        return act
    if act not in ACTIVATIONS:
        raise ValueError(f"unknown activation: {act}")
    return ACTIVATIONS[act]


class Activation(Layer):
    def __init__(self, activation: Union[str, Callable], **kwargs):
        super().__init__(**kwargs)
        self.activation_name = activation if isinstance(activation, str) else None
        self.fn = get_activation(activation)

    def call(self, params, x, *, training=False, rng=None):
        return self.fn(x)


class Dense(Layer):
    """Fully connected — ``keras/layers/Dense.scala``. Keras-1 signature:
    ``Dense(output_dim, init, activation, W_regularizer..., bias)``.
    Matmul accumulates in float32 on the MXU regardless of compute dtype."""

    def __init__(self, output_dim: int, init: str = "glorot_uniform",
                 activation=None, bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.output_dim = output_dim
        self.init = init
        self.activation = get_activation(activation)
        self.bias = bias

    def build(self, rng, input_shape):
        in_dim = input_shape[-1]
        w_key, _ = jax.random.split(rng)
        params = {"W": get_initializer(self.init)(
            w_key, (in_dim, self.output_dim), param_dtype())}
        if self.bias:
            params["b"] = jnp.zeros((self.output_dim,), param_dtype())
        return params

    def param_sharding(self, params):
        """Column-parallel TP: the kernel's output dim splits over the
        ``model`` axis (Megatron-style); GSPMD propagates the resulting
        feature sharding through the activation graph."""
        from jax.sharding import PartitionSpec as P
        from .....parallel.mesh import MODEL_AXIS
        spec = {"W": P(None, MODEL_AXIS)}
        if "b" in params:
            spec["b"] = P(MODEL_AXIS)
        return spec

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = jnp.matmul(x.astype(cd), params["W"].astype(cd),
                       preferred_element_type=jnp.float32)
        y = y.astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y

    def quantized_call(self, qp, x):
        """Static int8 path (inference runtime): activations quantize to the
        calibrated ``x_scale``, the matmul runs int8 x int8 -> int32 on the
        MXU, and one fused rescale restores float — the native replacement
        for OpenVINO's calibrated int8 FC (SURVEY §2.3)."""
        xq = jnp.clip(jnp.round(x / qp["x_scale"]), -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            xq, qp["W"], (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = y.astype(jnp.float32) * (qp["x_scale"] * qp["w_scale"])
        if self.bias:
            y = y + qp["b"]
        if self.activation is not None:
            y = self.activation(y)
        return y


class Dropout(Layer):
    """``keras/layers/Dropout.scala`` — inverted dropout, active only in
    training; a no-op under jit at inference so XLA removes it entirely."""

    def __init__(self, p: float, **kwargs):
        super().__init__(**kwargs)
        self.p = p

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: training dropout needs an rng")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, shape=x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class Flatten(Layer):
    """``keras/layers/Flatten.scala``."""

    def call(self, params, x, *, training=False, rng=None):
        return x.reshape(x.shape[0], -1)


class Reshape(Layer):
    """``keras/layers/Reshape.scala`` — target_shape excludes batch."""

    def __init__(self, target_shape: Tuple[int, ...], **kwargs):
        super().__init__(**kwargs)
        self.target_shape = tuple(target_shape)

    def call(self, params, x, *, training=False, rng=None):
        return x.reshape((x.shape[0],) + self.target_shape)


class Permute(Layer):
    """``keras/layers/Permute.scala`` — dims are 1-based over non-batch axes."""

    def __init__(self, dims: Tuple[int, ...], **kwargs):
        super().__init__(**kwargs)
        self.dims = tuple(dims)

    def call(self, params, x, *, training=False, rng=None):
        perm = (0,) + tuple(d for d in self.dims)
        return jnp.transpose(x, perm)


class RepeatVector(Layer):
    """``keras/layers/RepeatVector.scala`` — (B, D) -> (B, n, D)."""

    def __init__(self, n: int, **kwargs):
        super().__init__(**kwargs)
        self.n = n

    def call(self, params, x, *, training=False, rng=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1)


class Merge(Layer):
    """``keras/layers/Merge.scala`` — combine a list of inputs.
    modes: sum, mul, ave, max, min, concat, dot, cos."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1, **kwargs):
        super().__init__(**kwargs)
        self.mode = mode
        self.concat_axis = concat_axis

    def call(self, params, xs, *, training=False, rng=None):
        if not isinstance(xs, (list, tuple)):
            raise ValueError(f"{self.name}: Merge expects a list of inputs")
        m = self.mode
        if m == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if m == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if m == "ave":
            return sum(xs) / len(xs)
        if m == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if m == "min":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        if m == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if m == "dot":
            a, b = xs
            return jnp.sum(a * b, axis=-1, keepdims=True)
        if m == "cos":
            a, b = xs
            an = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + 1e-8)
            bn = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + 1e-8)
            return jnp.sum(an * bn, axis=-1, keepdims=True)
        raise ValueError(f"unknown merge mode {m}")


def merge(inputs, mode: str = "sum", concat_axis: int = -1, name=None):
    """Functional helper mirroring pyzoo's ``merge`` (layers/topology)."""
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(inputs)


class Select(Layer):
    """``keras/layers/Select.scala`` — pick index along a dim (1-based dims in
    the reference; here 0 = batch, negatives allowed)."""

    def __init__(self, dim: int, index: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
        self.index = index

    def call(self, params, x, *, training=False, rng=None):
        return jnp.take(x, self.index, axis=self.dim)


class Squeeze(Layer):
    def __init__(self, dim: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

    def call(self, params, x, *, training=False, rng=None):
        return jnp.squeeze(x, axis=self.dim)


class ExpandDim(Layer):
    def __init__(self, dim: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

    def call(self, params, x, *, training=False, rng=None):
        return jnp.expand_dims(x, axis=self.dim)


class Narrow(Layer):
    """``keras/layers/Narrow.scala`` — slice `length` elems from `offset`
    along `dim`."""

    def __init__(self, dim: int, offset: int, length: int = 1, **kwargs):
        super().__init__(**kwargs)
        self.dim, self.offset, self.length = dim, offset, length

    def call(self, params, x, *, training=False, rng=None):
        sl = [slice(None)] * x.ndim
        sl[self.dim] = slice(self.offset, self.offset + self.length)
        return x[tuple(sl)]


class Masking(Layer):
    """``keras/layers/Masking.scala`` — zero out timesteps equal to
    mask_value (soft masking; XLA-friendly, no ragged shapes)."""

    def __init__(self, mask_value: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.mask_value = mask_value

    def call(self, params, x, *, training=False, rng=None):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, 0.0)


class GaussianNoise(Layer):
    """``keras/layers/GaussianNoise.scala``."""

    def __init__(self, sigma: float, **kwargs):
        super().__init__(**kwargs)
        self.sigma = sigma

    def call(self, params, x, *, training=False, rng=None):
        if not training:
            return x
        return x + self.sigma * jax.random.normal(rng, x.shape, x.dtype)


class GaussianDropout(Layer):
    """``keras/layers/GaussianDropout.scala``."""

    def __init__(self, p: float, **kwargs):
        super().__init__(**kwargs)
        self.p = p

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.p <= 0:
            return x
        stddev = (self.p / (1.0 - self.p)) ** 0.5
        return x * (1.0 + stddev * jax.random.normal(rng, x.shape, x.dtype))


class TimeDistributed(Layer):
    """``keras/layers/TimeDistributed.scala`` — apply an inner layer to every
    timestep. Implemented by folding time into batch (static reshape keeps
    XLA happy and the MXU batched), not a Python loop."""

    def __init__(self, layer: Layer, **kwargs):
        super().__init__(**kwargs)
        self.layer = layer

    def build(self, rng, input_shape):
        inner = (input_shape[0],) + tuple(input_shape[2:])
        return {self.layer.name: self.layer.build(rng, inner)}

    def initial_state(self, input_shape):
        inner = (input_shape[0],) + tuple(input_shape[2:])
        s = self.layer.initial_state(inner)
        return {self.layer.name: s} if s else {}

    def apply(self, params, state, x, *, training=False, rng=None):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y, ns = self.layer.apply(params[self.layer.name],
                                 state.get(self.layer.name, {}) if state else {},
                                 flat, training=training, rng=rng)
        y = y.reshape((b, t) + y.shape[1:])
        return y, ({self.layer.name: ns} if ns else state)


class Highway(Layer):
    """``keras/layers/Highway.scala`` — y = t*h + (1-t)*x."""

    def __init__(self, activation="tanh", bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.activation = get_activation(activation)
        self.bias = bias

    def build(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        init = get_initializer("glorot_uniform")
        p = {"W": init(k1, (d, d), param_dtype()),
             "W_t": init(k2, (d, d), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((d,), param_dtype())
            # negative transform-gate bias: start as identity (standard highway init)
            p["b_t"] = jnp.full((d,), -2.0, param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        t = x @ params["W_t"]
        h = x @ params["W"]
        if self.bias:
            t = t + params["b_t"]
            h = h + params["b"]
        t = jax.nn.sigmoid(t)
        if self.activation is not None:
            h = self.activation(h)
        return t * h + (1.0 - t) * x


class SparseDense(Layer):
    """``keras/layers/SparseDense.scala`` — dense layer accepting one-hot /
    multi-hot sparse rows. TPU-native: the "sparse" input is a dense 0/1
    matrix; XLA maps the matmul onto the MXU which beats gather-scatter."""

    def __init__(self, output_dim: int, init="glorot_uniform", activation=None,
                 bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self._dense = None
        self.output_dim, self.init = output_dim, init
        self.activation, self.bias = activation, bias

    def build(self, rng, input_shape):
        self._dense = Dense(self.output_dim, init=self.init,
                            activation=self.activation, bias=self.bias,
                            name=self.name + "_d")
        return self._dense.build(rng, input_shape)

    def call(self, params, x, *, training=False, rng=None):
        return self._dense.call(params, x, training=training, rng=rng)
