"""Transformer layers — parity with the reference's attention stack
(``pipeline/api/keras/layers/TransformerLayer.scala:56``, ``BERT.scala:66``,
pyzoo ``pipeline/api/keras/layers/self_attention.py``).

* ``MultiHeadSelfAttention`` — fused QKV projection (one (B*T, H) x (H, 3H)
  matmul onto the MXU) + the swappable attention core in
  ``ops/attention.py``.
* ``TransformerBlock`` — post-LN residual block (attention → add&norm →
  gelu FFN → add&norm), the layout both the reference's GPT-style
  TransformerLayer and BERT use.
* ``TransformerLayer`` — word+position embeddings + N causal blocks
  (``bidirectional=False`` ≙ the reference's maskAttention GPT mode).
* ``BERT`` — word+position+token-type embeddings, N bidirectional blocks with
  an attention mask input, plus the tanh pooler over [CLS].
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.attention import (dot_product_attention,
                                             merge_heads, split_heads)
from ..engine import Layer, compute_dtype, get_initializer, param_dtype
from .normalization import LayerNorm


def _dense_params(rng, d_in, d_out, init="glorot_uniform"):
    return {"W": get_initializer(init)(rng, (d_in, d_out), param_dtype()),
            "b": jnp.zeros((d_out,), param_dtype())}


def _dense(p, x, cd):
    y = jnp.einsum("...d,dk->...k", x.astype(cd), p["W"].astype(cd),
                   preferred_element_type=jnp.float32).astype(cd)
    return y + p["b"].astype(cd)


def _dropout(x, rate, rng, training):
    if not training or rate <= 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _stack_param_sharding(blocks, params, embed_keys=()):
    """Shared TP spec for the transformer stacks: per-block specs from the
    block layers, embedding TABLES sharded over their hidden dim (the same
    ``P(None, model)`` rule the standalone ``Embedding`` layer declares —
    the word table is BERT's largest tensor and must not replicate per
    model shard), everything else replicated."""
    from jax.sharding import PartitionSpec as P

    from .....parallel.mesh import MODEL_AXIS
    spec = {}
    for k, v in params.items():
        if k.startswith("block"):
            continue
        spec[k] = (P(None, MODEL_AXIS) if k in embed_keys
                   else jax.tree.map(lambda _: None, v))
    for i, blk in enumerate(blocks):
        spec[f"block{i}"] = blk.param_sharding(params[f"block{i}"])
    return spec


class MultiHeadSelfAttention(Layer):
    """Fused-QKV multi-head self-attention. Input (B, T, H) (optionally with a
    (B, 1, 1, T) keep-mask) → (B, T, H)."""

    def __init__(self, hidden_size: int, n_head: int, causal: bool = False,
                 attn_drop: float = 0.0, out_drop: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        if hidden_size % n_head != 0:
            raise ValueError(f"hidden_size {hidden_size} not divisible by "
                             f"n_head {n_head}")
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.causal = causal
        self.attn_drop = attn_drop
        self.out_drop = out_drop

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        return {"qkv": _dense_params(k1, self.hidden_size, 3 * self.hidden_size),
                "proj": _dense_params(k2, self.hidden_size, self.hidden_size)}

    def param_sharding(self, params):
        """Attention TP: fused QKV column-parallel (output dim over
        ``model``), output projection row-parallel. Numerics equal the
        replicated form (equality-tested in ``test_parallel``). NOTE the
        fused ``[q|k|v]`` column layout is NOT head-interleaved, so GSPMD
        reshards the qkv activation at the head split instead of keeping
        whole heads shard-local (true Megatron fusion interleaves per
        head — future work); the annotation still shards the two big
        matmuls and their gradients."""
        from jax.sharding import PartitionSpec as P

        from .....parallel.mesh import MODEL_AXIS
        return {"qkv": {"W": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)},
                "proj": {"W": P(MODEL_AXIS, None), "b": P()}}

    @staticmethod
    def _kv_mask(mask):
        """Reduce a broadcastable attention mask to the (B, Tk) key-padding
        form the flash kernel streams blockwise; None if it can't be (a
        genuinely per-query mask stays on the XLA op)."""
        if mask is None:
            return None
        if mask.ndim == 2:                      # (B, Tk)
            return mask
        if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
            return mask[:, 0, 0, :]             # (B, 1, 1, Tk)
        return None

    #: auto mode hands sequences this long to the flash kernel: below it the
    #: fused XLA softmax-attention wins (measured on a v5e, BERT-base bf16:
    #: XLA is 1.11x flash at T=512 and 1.06x at T=1024), at/above it the
    #: O(T²) HBM materialization dominates — XLA fails to even compile
    #: BERT-base at T=2048 on a 16 GB chip, where the blockwise kernel
    #: trains fine (52k tok/s; 4k/32k numbers in BENCH long_context).
    FLASH_AUTO_MIN_SEQ = 2048

    def _use_flash(self, mask, drop, seq_len: int) -> bool:
        """The pallas flash kernel covers key-padding masks (the BERT
        ``attention_mask`` form) and mask-free attention, forward AND
        backward; in-kernel dropout and per-query masks stay on the XLA op.
        ``zoo.pallas.attention``: True/False force it; ``auto`` (default)
        enables it on TPU backends for sequences ≥ FLASH_AUTO_MIN_SEQ (the
        CPU interpreter path is for tests, not speed)."""
        if drop > 0.0:
            return False
        if mask is not None and self._kv_mask(mask) is None:
            return False
        try:
            from .....parallel import mesh as mesh_lib
            if mesh_lib.global_mesh().shape[mesh_lib.MODEL_AXIS] > 1:
                # pallas_call has no SPMD partitioning rule: model-sharded
                # activations must stay on the XLA op (which GSPMD splits)
                return False
        # no mesh is constructible here (e.g. an odd device count) — the
        # single-device pallas decision below is still valid
        except Exception:  # zoolint: disable=ZL007
            pass
        from .....common.context import tri_state_conf
        flag = tri_state_conf("zoo.pallas.attention")
        if flag == "auto":
            return (jax.default_backend() == "tpu"
                    and seq_len >= self.FLASH_AUTO_MIN_SEQ)
        return flag

    def _seq_fallback(self, reason: str, probe: bool = False):
        """A seq mesh exists but this call can't ride it. Default: warn ONCE
        — falling back to full O(T^2) attention at long-context scale is an
        OOM surprise, not a detail. ``zoo.seq.strict=True`` — or a
        training-loop-forced mode (``zoo.train.seq_attention``, which is
        an explicit contract): raise instead (VERDICT r4 weak #6 — a user
        who built a seq mesh must not silently get zero sequence
        parallelism)."""
        from .....common.context import get_zoo_context
        from ..seq_pipe import forced_seq_mode
        try:
            strict = bool(get_zoo_context().get("zoo.seq.strict", False))
        except Exception:
            strict = False
        strict = strict or forced_seq_mode() in ("ring", "ulysses")
        if strict and not probe:
            raise RuntimeError(
                f"{self.name}: zoo.seq.strict is set and {reason} — "
                f"attention cannot ride the seq mesh (it would silently "
                f"fall back to full XLA attention)")
        if not getattr(self, "_warned_no_ring", False) and not probe:
            import logging
            logging.getLogger("analytics_zoo_tpu.attention").warning(
                "%s: seq-axis mesh active but %s — full O(T^2) attention "
                "for this layer (no sequence parallelism)", self.name,
                reason)
            self._warned_no_ring = True
        return None

    def _ring_mesh(self, mask, drop, seq_len, rng=None):
        """Sequence parallelism from the LAYER API: on a mesh with a ``seq``
        axis, attention shards the sequence dim over ICI — KV-rotation ring
        or Ulysses head/seq all-to-all (``parallel/ring_attention.py``) —
        instead of gathering the full sequence per chip: the long-context
        path (SURVEY §5). Key-padding masks (the BERT ``attention_mask``
        form) stream with the ring / all-gather under Ulysses; attention
        dropout runs in-ring with block-position-keyed masks. Only
        genuinely per-query masks (and dropout without an rng) stay on the
        full XLA op."""
        from ..seq_pipe import forced_seq_mode
        if forced_seq_mode() == "off":
            # inside a pipeline stage (or an explicit disable scope):
            # no seq routing, no warning — the caller made the choice
            return None
        try:
            from .....parallel import mesh as mesh_lib
            mesh = mesh_lib.global_mesh()
            n_seq = mesh.shape[mesh_lib.SEQ_AXIS]
        except Exception:
            return None
        if n_seq <= 1:
            return None
        # shape-inference probes (placeholder batch dims) must neither warn
        # nor raise strict errors — and must not burn the warn-once flag
        # before the real call gets to warn
        from ..engine import in_shape_probe
        probe = in_shape_probe()
        if drop > 0.0 and rng is None:
            return self._seq_fallback(
                f"attn_drop={drop} with no rng (training=True without a "
                f"PRNG key cannot draw in-ring dropout masks)",
                probe=probe)
        if mask is not None and self._kv_mask(mask) is None:
            return self._seq_fallback(
                "the mask is per-query (not reducible to (B, Tk) "
                "key-padding form)", probe=probe)
        batch, t = seq_len  # (B, T): both must split over their axes
        if t % n_seq == 0 and batch % mesh.shape[mesh_lib.DATA_AXIS] == 0:
            return mesh
        return self._seq_fallback(
            f"shapes can't split (T={t} over seq={n_seq}, B={batch} over "
            f"data={mesh.shape[mesh_lib.DATA_AXIS]})", probe=probe)

    def _seq_routing(self, n_seq: int) -> str:
        """``zoo.seq.mode``: ``ring`` (default), ``ulysses``, or ``auto``
        (ulysses when n_head divides the seq axis — two all-to-alls beat
        n-1 ppermutes when the dense local score block fits). A
        training-loop-forced mode (``zoo.train.seq_attention``, scoped
        over the step trace) wins over the layer-level knob."""
        from .....common.context import get_zoo_context
        from ..seq_pipe import forced_seq_mode
        forced = forced_seq_mode()
        if forced in ("ring", "ulysses"):
            mode = forced
        else:
            try:
                mode = str(get_zoo_context().get("zoo.seq.mode",
                                                 "ring")).lower()
            except Exception:
                mode = "ring"
        if mode not in ("ring", "ulysses", "auto"):
            raise ValueError(f"zoo.seq.mode must be ring|ulysses|auto, "
                             f"got {mode!r}")
        if mode == "ulysses" and self.n_head % n_seq != 0:
            raise ValueError(
                f"zoo.seq.mode=ulysses needs n_head ({self.n_head}) "
                f"divisible by the seq axis ({n_seq})")
        if mode == "auto":
            mode = "ulysses" if self.n_head % n_seq == 0 else "ring"
        return mode

    def call(self, params, x, *, training=False, rng=None):
        mask = None
        if isinstance(x, (list, tuple)):
            x, mask = x
        cd = compute_dtype()
        qkv = _dense(params["qkv"], x, cd)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        qh, kh, vh = (split_heads(a, self.n_head) for a in (q, k, v))
        drop = self.attn_drop if training else 0.0
        ring_mesh = self._ring_mesh(mask, drop, (qh.shape[0], qh.shape[2]),
                                    rng=r1)
        if ring_mesh is not None:
            from .....parallel import mesh as mesh_lib
            from .....parallel.ring_attention import (ring_self_attention,
                                                      ulysses_self_attention)
            kv_mask = self._kv_mask(mask)
            if kv_mask is not None:
                kv_mask = kv_mask.astype(jnp.bool_)
            n_seq = ring_mesh.shape[mesh_lib.SEQ_AXIS]
            route = (ulysses_self_attention
                     if self._seq_routing(n_seq) == "ulysses"
                     else ring_self_attention)
            out = route(qh, kh, vh, mesh=ring_mesh, causal=self.causal,
                        mask=kv_mask, dropout_rate=drop,
                        dropout_rng=r1 if drop > 0.0 else None)
        elif self._use_flash(mask, drop, qh.shape[2]):
            from .....ops.pallas import flash_attention
            out = flash_attention(qh, kh, vh, mask=self._kv_mask(mask),
                                  causal=self.causal)
        else:
            out = dot_product_attention(qh, kh, vh, mask=mask,
                                        causal=self.causal,
                                        dropout_rate=drop, dropout_rng=r1)
        out = _dense(params["proj"], merge_heads(out), cd)
        return _dropout(out, self.out_drop, r2, training)


class TransformerBlock(Layer):
    """Post-LN residual block: x = LN1(x + Attn(x)); x = LN2(x + FFN(x)).
    FFN = gelu (``TransformerLayer.scala`` uses gelu, as does BERT)."""

    def __init__(self, hidden_size: int, n_head: int,
                 intermediate_size: Optional[int] = None,
                 causal: bool = False, hidden_drop: float = 0.0,
                 attn_drop: float = 0.0, epsilon: float = 1e-5,
                 gelu_approximate: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.hidden_drop = hidden_drop
        self.gelu_approximate = gelu_approximate  # BERT parity needs exact
        self.attn = MultiHeadSelfAttention(
            hidden_size, n_head, causal=causal, attn_drop=attn_drop,
            out_drop=hidden_drop, name=(kwargs.get("name") or "tb") + "_attn")
        self.ln1 = LayerNorm(epsilon=epsilon)
        self.ln2 = LayerNorm(epsilon=epsilon)

    def build(self, rng, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) else input_shape
        k1, k2, k3, k4, k5 = jax.random.split(rng, 5)
        return {
            "attn": self.attn.build(k1, shape),
            "ln1": self.ln1.build(k2, shape),
            "fc": _dense_params(k3, self.hidden_size, self.intermediate_size),
            "out": _dense_params(k4, self.intermediate_size, self.hidden_size),
            "ln2": self.ln2.build(k5, shape),
        }

    def param_sharding(self, params):
        """Megatron block TP: attention specs from the attention layer, MLP
        fc column-parallel / out row-parallel, LayerNorms replicated."""
        from jax.sharding import PartitionSpec as P

        from .....parallel.mesh import MODEL_AXIS
        return {
            "attn": self.attn.param_sharding(params["attn"]),
            "ln1": jax.tree.map(lambda _: None, params["ln1"]),
            "fc": {"W": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)},
            "out": {"W": P(MODEL_AXIS, None), "b": P()},
            "ln2": jax.tree.map(lambda _: None, params["ln2"]),
        }

    def call(self, params, x, *, training=False, rng=None):
        mask = None
        if isinstance(x, (list, tuple)):
            x, mask = x
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        cd = compute_dtype()
        a = self.attn.call(params["attn"], [x, mask] if mask is not None else x,
                           training=training, rng=r1)
        x = self.ln1.call(params["ln1"], x + a)
        h = jax.nn.gelu(_dense(params["fc"], x, cd),
                        approximate=self.gelu_approximate)
        h = _dropout(_dense(params["out"], h, cd), self.hidden_drop, r2,
                     training)
        return self.ln2.call(params["ln2"], x + h)


class TransformerLayer(Layer):
    """GPT-style decoder stack — ``TransformerLayer.scala:56`` /
    pyzoo ``self_attention.py``. Input int ids (B, T) → hidden states
    (B, T, H). ``bidirectional=False`` applies the causal mask (the
    reference's ``maskAttention``)."""

    def __init__(self, vocab: int, seq_len: int, n_block: int = 12,
                 hidden_size: int = 768, n_head: int = 12,
                 hidden_drop: float = 0.1, attn_drop: float = 0.1,
                 embedding_drop: float = 0.1, bidirectional: bool = False,
                 initializer_range: float = 0.02, **kwargs):
        super().__init__(**kwargs)
        self.vocab = vocab
        self.seq_len = seq_len
        self.n_block = n_block
        self.hidden_size = hidden_size
        self.embedding_drop = embedding_drop
        self.initializer_range = initializer_range
        self.blocks = [
            TransformerBlock(hidden_size, n_head, causal=not bidirectional,
                             hidden_drop=hidden_drop, attn_drop=attn_drop,
                             name=f"{self.name}_block{i}")
            for i in range(n_block)
        ]

    def build(self, rng, input_shape):
        keys = jax.random.split(rng, self.n_block + 2)
        std = self.initializer_range
        p: Dict[str, Any] = {
            "wte": jax.random.normal(keys[0], (self.vocab, self.hidden_size),
                                     param_dtype()) * std,
            "wpe": jax.random.normal(keys[1], (self.seq_len, self.hidden_size),
                                     param_dtype()) * std,
        }
        h_shape = (input_shape[0], input_shape[1], self.hidden_size)
        for i, blk in enumerate(self.blocks):
            p[f"block{i}"] = blk.build(keys[i + 2], h_shape)
        return p

    def param_sharding(self, params):
        return _stack_param_sharding(self.blocks, params,
                                     embed_keys=("wte", "wpe"))

    def call(self, params, x, *, training=False, rng=None):
        ids = x.astype(jnp.int32)
        t = ids.shape[1]
        h = (jnp.take(params["wte"], ids, axis=0)
             + params["wpe"][None, :t, :]).astype(compute_dtype())
        r = rng
        if rng is not None:
            r, re = jax.random.split(rng)
            h = _dropout(h, self.embedding_drop, re, training)
        for i, blk in enumerate(self.blocks):
            br = jax.random.fold_in(r, i) if r is not None else None
            h = blk.call(params[f"block{i}"], h, training=training, rng=br)
        return h


class BERT(Layer):
    """BERT encoder — ``BERT.scala:66``. Input
    ``[token_ids, token_type_ids, position_ids, attention_mask]`` (mask is
    (B, 1, 1, T), 1.0 = attend) → ``[sequence_output, pooled_output]``."""

    def __init__(self, vocab: int = 40990, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12, seq_len: int = 512,
                 intermediate_size: int = 3072, hidden_drop: float = 0.1,
                 attn_drop: float = 0.1, initializer_range: float = 0.02,
                 type_vocab: int = 2, **kwargs):
        super().__init__(**kwargs)
        self.vocab = vocab
        self.hidden_size = hidden_size
        self.n_block = n_block
        self.seq_len = seq_len
        self.type_vocab = type_vocab
        self.hidden_drop = hidden_drop
        self.initializer_range = initializer_range
        self.emb_ln = LayerNorm(epsilon=1e-12)
        self.blocks = [
            TransformerBlock(hidden_size, n_head,
                             intermediate_size=intermediate_size,
                             causal=False, hidden_drop=hidden_drop,
                             attn_drop=attn_drop, epsilon=1e-12,
                             gelu_approximate=False,  # BERT's erf-form gelu
                             name=f"{self.name}_block{i}")
            for i in range(n_block)
        ]

    def build(self, rng, input_shape):
        shapes = input_shape if isinstance(input_shape, list) else [input_shape]
        b, t = shapes[0][0], shapes[0][1]
        keys = jax.random.split(rng, self.n_block + 5)
        std = self.initializer_range
        p: Dict[str, Any] = {
            "word": jax.random.normal(keys[0], (self.vocab, self.hidden_size),
                                      param_dtype()) * std,
            "position": jax.random.normal(
                keys[1], (self.seq_len, self.hidden_size), param_dtype()) * std,
            "token_type": jax.random.normal(
                keys[2], (self.type_vocab, self.hidden_size),
                param_dtype()) * std,
            "emb_ln": self.emb_ln.build(keys[3], (b, t, self.hidden_size)),
            "pooler": _dense_params(keys[4], self.hidden_size,
                                    self.hidden_size),
        }
        for i, blk in enumerate(self.blocks):
            p[f"block{i}"] = blk.build(keys[i + 5] if self.n_block else keys[4],
                                       (b, t, self.hidden_size))
        return p

    def param_sharding(self, params):
        return _stack_param_sharding(
            self.blocks, params,
            embed_keys=("word", "position", "token_type"))

    def call(self, params, x, *, training=False, rng=None):
        if not isinstance(x, (list, tuple)) or len(x) != 4:
            raise ValueError(
                f"{self.name}: BERT expects [token_ids, token_type_ids, "
                f"position_ids, attention_mask]")
        ids, token_type, pos, mask = x
        cd = compute_dtype()
        # cast tables to the compute dtype BEFORE the gather: halves the
        # gather read and (more importantly) the backward scatter-add
        # traffic under bf16 — the table-sized cast is one cheap pass
        h = (jnp.take(params["word"].astype(cd), ids.astype(jnp.int32),
                      axis=0)
             + jnp.take(params["position"].astype(cd),
                        pos.astype(jnp.int32), axis=0)
             + jnp.take(params["token_type"].astype(cd),
                        token_type.astype(jnp.int32), axis=0))
        h = self.emb_ln.call(params["emb_ln"], h).astype(cd)
        r = rng
        if rng is not None:
            r, re = jax.random.split(rng)
            h = _dropout(h, self.hidden_drop, re, training)
        if mask is not None and mask.ndim == 2:  # (B, T) → (B, 1, 1, T)
            mask = mask[:, None, None, :]
        for i, blk in enumerate(self.blocks):
            br = jax.random.fold_in(r, i) if r is not None else None
            h = blk.call(params[f"block{i}"], [h, mask], training=training,
                         rng=br)
        pooled = jnp.tanh(_dense(params["pooler"], h[:, 0, :], cd))
        return [h, pooled]
