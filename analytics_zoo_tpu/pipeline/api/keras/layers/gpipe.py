"""GPipe layer — pipeline parallelism as a composable Keras-style layer.

The reference has no pipeline parallelism at all (SURVEY §2.4: "NO — no
stage partitioner / microbatch scheduler exists"); this is greenfield TPU
design. The schedule itself lives in ``parallel/pipeline.py`` (shard_map +
ppermute over the ``pipe`` mesh axis); this wrapper stacks ``num_stages``
homogeneous stage layers into one ``(S, ...)`` param tree so the model code
is a single layer that runs pipelined on a ``pipe=S`` mesh and sequentially
(identical math, ``lax.scan`` over stages) everywhere else.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .....parallel import mesh as mesh_lib
from .....parallel.pipeline import (gpipe_apply, hetero_gpipe_apply,
                                    sequential_apply)
from ..engine import Layer, compute_dtype, param_dtype


class GPipe(Layer):
    """A stack of ``num_stages`` homogeneous layers over the ``pipe`` axis.

    ``stage_factory()`` builds ONE stage (e.g. ``lambda:
    TransformerBlock(8, 2)``); stages must preserve shape (input == output,
    the transformer-stack case PP exists for) and be stateless.

    REAL models pipeline by composition: put the heterogeneous edges
    OUTSIDE the GPipe layer — ``Sequential([Embedding, GPipe(block, S),
    LayerNorm, head])`` — and only the homogeneous stack rides the
    schedule while the edges replicate over ``pipe`` (the same split
    praxis-style TPU pipelining uses; equality-tested vs pure DP in
    ``test_pipeline_parallel.py::test_real_model_with_embedding_front_and_head_pipelines``). On a
    ``pipe=P`` mesh (``num_stages`` a multiple of P) each rank owns
    ``num_stages/P`` consecutive stages, applied back-to-back per tick,
    and microbatches flow through the GPipe schedule; on a ``pipe=1`` mesh
    the stack runs sequentially — the model is portable either way
    (bit-identical for deterministic stages; stochastic stages draw
    decorrelated per-(stage, microbatch) keys under the schedule, so
    dropout masks differ across placements).
    """

    def __init__(self, stage_factory: Callable, num_stages: int,
                 n_microbatches: Optional[int] = None, remat: bool = False,
                 **kwargs):
        super().__init__(**kwargs)
        if num_stages < 1:
            raise ValueError(f"num_stages={num_stages} < 1")
        self.stage_factory = stage_factory
        self.num_stages = num_stages
        self.n_microbatches = n_microbatches
        #: the GPipe paper's memory schedule: re-materialize stage
        #: activations in the backward pass, so only the stage-BOUNDARY
        #: activations stay live per (tick, microbatch) instead of every
        #: intermediate — raise n_microbatches without the activation bill
        self.remat = remat
        self.stage = stage_factory()  # template instance: defines the math
        self._warned_fallback = False

    def build(self, rng, input_shape):
        keys = jax.random.split(rng, self.num_stages)
        trees = []
        for i in range(self.num_stages):
            stage = self.stage_factory() if i else self.stage
            if stage.initial_state(input_shape):
                raise ValueError(
                    f"{self.name}: pipeline stages must be stateless")
            p = stage.build(keys[i], input_shape)
            out_shape = stage.output_shape_for(p, {}, input_shape)
            if tuple(out_shape[1:]) != tuple(input_shape[1:]):
                raise ValueError(
                    f"{self.name}: stage must preserve shape, got "
                    f"{tuple(input_shape[1:])} -> {tuple(out_shape[1:])}")
            trees.append(p)
        return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)

    def param_sharding(self, params):
        """Stage dim over ``pipe``; inner dims replicated (composing the
        stage's own model-axis rules inside PP is future work)."""
        return jax.tree.map(lambda _: P(mesh_lib.PIPE_AXIS), params)

    def _stage_fn(self, training):
        def fn(p_stage, h, rng):
            return self.stage.call(p_stage, h, training=training, rng=rng)
        # prevent_cse=False: the stage only ever runs inside lax.scan
        # bodies, where the CSE-prevention barriers are unnecessary and
        # cost fusion (per the jax.checkpoint docs)
        return (jax.checkpoint(fn, prevent_cse=False) if self.remat
                else fn)

    def call(self, params, x, *, training=False, rng=None):
        mesh = mesh_lib.global_mesh()
        S = mesh.shape[mesh_lib.PIPE_AXIS]
        fn = self._stage_fn(training)
        # the scan carry must be dtype-stable: enter at the compute dtype the
        # stages will emit (bfloat16 under a mixed-precision policy)
        x = x.astype(compute_dtype())
        if S > 1:
            if self.num_stages % S != 0:
                raise ValueError(
                    f"{self.name}: num_stages={self.num_stages} must be a "
                    f"multiple of the pipe axis size {S}")
            n_micro = self.n_microbatches or S
            dp = mesh.shape[mesh_lib.DATA_AXIS]
            B = x.shape[0]
            # batches the schedule can't split (shape inference's B=1
            # probe, ragged predict tails) run the sequential path — the
            # math is identical, only the chip placement differs
            if B % dp == 0 and (B // dp) % n_micro == 0:
                return gpipe_apply(fn, params, x, mesh=mesh,
                                   n_micro=n_micro, rng=rng,
                                   stages_per_rank=self.num_stages // S)
            if B > dp and not self._warned_fallback:
                # a real batch (not the B=1 probe / tiny tail) losing the
                # pipeline is a silent S-times perf cliff — say so once
                import logging
                logging.getLogger("analytics_zoo_tpu.gpipe").warning(
                    "%s: batch %d (per-shard %s) not divisible by "
                    "n_microbatches=%d — running stages SEQUENTIALLY on the "
                    "pipe=%d mesh; pick a divisible batch size to pipeline",
                    self.name, B, B // dp if B % dp == 0 else f"{B}/{dp}",
                    n_micro, S)
                self._warned_fallback = True
        return sequential_apply(fn, params, x, self.num_stages, rng=rng)


class Pipeline(Layer):
    """HETEROGENEOUS pipeline parallelism: arbitrary layer cuts as stages.

    ``Pipeline(stages=[[Embedding(...)], [TransformerBlock(...)], ...,
    [LayerNorm(), Dense(...)]])`` — each stage is a list of layers (or a
    single layer); stages may have DIFFERENT param trees and DIFFERENT
    input/output shapes, so a real model (embedding front → blocks → head)
    pipelines end to end as one layer (the homogeneous ``GPipe`` above
    covers the stacked-identical-blocks case; the reference has no pipeline
    parallelism at all, SURVEY §2.4).

    Mechanics (see ``parallel/pipeline.py::hetero_gpipe_apply``): per-stage
    params ravel into rows of one ``(S, L)`` buffer sharded over ``pipe``
    (each rank materializes only its row), activations cross stage
    boundaries in a common ``(B_micro, W)`` float32 wire format, and each
    pipe rank executes its stage via ``lax.switch``. On a mesh without a
    ``pipe`` axis (or shapes the schedule can't split, e.g. the B=1 probe)
    the stages run sequentially — identical math, one device.

    Requirements: ``len(stages)`` must EQUAL the pipe-axis size when
    pipelined; stages must be stateless; all params share one dtype.
    """

    def __init__(self, stages, n_microbatches: Optional[int] = None,
                 remat: bool = False, **kwargs):
        super().__init__(**kwargs)
        if not stages:
            raise ValueError("Pipeline needs at least one stage")
        self.stages = [list(s) if isinstance(s, (list, tuple)) else [s]
                       for s in stages]
        self.num_stages = len(self.stages)
        self.n_microbatches = n_microbatches
        self.remat = remat  # see GPipe.remat
        self._warned_fallback = False

    def build(self, rng, input_shape):
        pdt = param_dtype()
        shape = tuple(input_shape)
        keys = jax.random.split(rng, sum(len(s) for s in self.stages) + 1)
        ki = 0
        self._meta = []  # per stage: dict(leaves, treedef, in/out feat shape)
        trees_flat = []
        for si, layers in enumerate(self.stages):
            in_shape = shape
            stage_trees = []
            for lyr in layers:
                if lyr.initial_state(shape):
                    raise ValueError(
                        f"{self.name}: pipeline stages must be stateless "
                        f"({lyr.name} carries state)")
                p = lyr.build(keys[ki], shape)
                ki += 1
                shape = lyr.output_shape_for(p, {}, shape)
                stage_trees.append(p)
            leaves, treedef = jax.tree_util.tree_flatten(stage_trees)
            for l in leaves:
                if l.dtype != pdt:
                    raise ValueError(
                        f"{self.name}: all stage params must be "
                        f"{pdt.__name__ if hasattr(pdt, '__name__') else pdt}"
                        f", got {l.dtype}")
            self._meta.append({
                "treedef": treedef,
                "shapes": [tuple(l.shape) for l in leaves],
                "sizes": [int(np.prod(l.shape)) if l.shape else 1
                          for l in leaves],
                "in_feat": tuple(in_shape[1:]),
                "out_feat": tuple(shape[1:]),
            })
            trees_flat.append(leaves)
        self._out_shape = tuple(shape)
        self._wire = max(
            [int(np.prod(m["in_feat"])) for m in self._meta]
            + [int(np.prod(self._meta[-1]["out_feat"]))])
        L = max(sum(m["sizes"]) for m in self._meta)
        rows = []
        # ragged per-stage trees (different layer shapes) — vmap does not
        # apply; this runs once at build time, not in the step
        for leaves, m in zip(trees_flat, self._meta):  # zoolint: disable=ZL005
            vec = (jnp.concatenate([jnp.ravel(l) for l in leaves])
                   if leaves else jnp.zeros((0,), pdt))
            rows.append(jnp.pad(vec, (0, L - vec.shape[0])))
        return {"stack": jnp.stack(rows)}

    def param_sharding(self, params):
        return {"stack": P(mesh_lib.PIPE_AXIS)}

    def output_shape_for(self, params, state, input_shape):
        # build() already chained the per-stage shape inference
        return (input_shape[0],) + self._out_shape[1:]

    def _unpack(self, si, vec):
        """Stage ``si``'s layer param trees out of its (L,) row — static
        slicing, so each lax.switch branch carries only its own layout."""
        m = self._meta[si]
        leaves, off = [], 0
        for shp, size in zip(m["shapes"], m["sizes"]):
            leaves.append(jax.lax.dynamic_slice_in_dim(
                vec, off, size).reshape(shp))
            off += size
        return jax.tree_util.tree_unflatten(m["treedef"], leaves)

    def _to_wire(self, x):
        """Flatten + pad the batch into the common (B, W) f32 wire format."""
        b = x.shape[0]
        in_sz = int(np.prod(self._meta[0]["in_feat"]))
        xw = x.reshape(b, in_sz).astype(jnp.float32)
        return jnp.pad(xw, ((0, 0), (0, self._wire - in_sz)))

    def _from_wire(self, out):
        """Unpad + reshape the final wire buffer to the model output."""
        out_feat = self._meta[-1]["out_feat"]
        out_sz = int(np.prod(out_feat))
        return (out[:, :out_sz].reshape((out.shape[0],) + out_feat)
                .astype(compute_dtype()))

    def _stage_fn(self, si, training, in_scan=True):
        """Wire-format stage: unpack params, unpad+reshape the activation,
        run the stage's layers, flatten+pad back to the wire width.
        ``in_scan``: the pipelined path runs stages inside ``lax.scan``
        where remat can skip the CSE barriers; the sequential Python-loop
        path must KEEP them (prevent_cse=True) or XLA merges the
        rematerialized forward with the original and the memory savings
        silently vanish."""
        m = self._meta[si]
        in_sz = int(np.prod(m["in_feat"]))
        out_sz = int(np.prod(m["out_feat"]))
        layers = self.stages[si]

        def fn(vec, h_wire, rng=None):
            trees = self._unpack(si, vec)
            b = h_wire.shape[0]
            h = h_wire[:, :in_sz].reshape((b,) + m["in_feat"])
            for j, (lyr, p) in enumerate(zip(layers, trees)):
                lrng = (jax.random.fold_in(jax.random.fold_in(rng, si), j)
                        if rng is not None else None)
                h = lyr.call(p, h, training=training, rng=lrng)
            h = h.astype(jnp.float32).reshape(b, out_sz)
            return jnp.pad(h, ((0, 0), (0, self._wire - out_sz)))

        if self.remat:
            return jax.checkpoint(fn, prevent_cse=not in_scan)
        return fn

    def call(self, params, x, *, training=False, rng=None):
        mesh = mesh_lib.global_mesh()
        S = mesh.shape[mesh_lib.PIPE_AXIS]
        if S > 1:
            if self.num_stages != S:
                raise ValueError(
                    f"{self.name}: {self.num_stages} stages on a pipe={S} "
                    f"mesh — heterogeneous stages need exactly one stage "
                    f"per pipe rank")
            n_micro = self.n_microbatches or S
            dp = mesh.shape[mesh_lib.DATA_AXIS]
            B = x.shape[0]
            if B % dp == 0 and (B // dp) % n_micro == 0:
                fns = [self._stage_fn(j, training)
                       for j in range(self.num_stages)]
                out = hetero_gpipe_apply(fns, params["stack"],
                                         self._to_wire(x), mesh=mesh,
                                         n_micro=n_micro, rng=rng)
                return self._from_wire(out)
            if B > dp and not self._warned_fallback:
                import logging
                logging.getLogger("analytics_zoo_tpu.gpipe").warning(
                    "%s: batch %d not schedulable over pipe=%d "
                    "(n_micro=%d) — running stages SEQUENTIALLY",
                    self.name, B, S, n_micro)
                self._warned_fallback = True
        # sequential path: the SAME wire-format stage fns applied in order
        # (one shared per-stage runner, so the placements cannot diverge
        # numerically) — also the B=1 probe path
        h = self._to_wire(x)
        for si in range(self.num_stages):
            h = self._stage_fn(si, training, in_scan=False)(
                params["stack"][si], h, rng=rng)
        return self._from_wire(h)
