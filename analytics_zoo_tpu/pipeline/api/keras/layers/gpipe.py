"""GPipe layer — pipeline parallelism as a composable Keras-style layer.

The reference has no pipeline parallelism at all (SURVEY §2.4: "NO — no
stage partitioner / microbatch scheduler exists"); this is greenfield TPU
design. The schedule itself lives in ``parallel/pipeline.py`` (shard_map +
ppermute over the ``pipe`` mesh axis); this wrapper stacks ``num_stages``
homogeneous stage layers into one ``(S, ...)`` param tree so the model code
is a single layer that runs pipelined on a ``pipe=S`` mesh and sequentially
(identical math, ``lax.scan`` over stages) everywhere else.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....parallel import mesh as mesh_lib
from .....parallel.pipeline import gpipe_apply, sequential_apply
from ..engine import Layer, compute_dtype


class GPipe(Layer):
    """A stack of ``num_stages`` homogeneous layers over the ``pipe`` axis.

    ``stage_factory()`` builds ONE stage (e.g. ``lambda:
    TransformerBlock(8, 2)``); stages must preserve shape (input == output,
    the transformer-stack case PP exists for) and be stateless.

    REAL models pipeline by composition: put the heterogeneous edges
    OUTSIDE the GPipe layer — ``Sequential([Embedding, GPipe(block, S),
    LayerNorm, head])`` — and only the homogeneous stack rides the
    schedule while the edges replicate over ``pipe`` (the same split
    praxis-style TPU pipelining uses; equality-tested vs pure DP in
    ``test_pipeline_parallel.py::test_real_model_with_embedding_front_and_head_pipelines``). On a
    ``pipe=P`` mesh (``num_stages`` a multiple of P) each rank owns
    ``num_stages/P`` consecutive stages, applied back-to-back per tick,
    and microbatches flow through the GPipe schedule; on a ``pipe=1`` mesh
    the stack runs sequentially — the model is portable either way
    (bit-identical for deterministic stages; stochastic stages draw
    decorrelated per-(stage, microbatch) keys under the schedule, so
    dropout masks differ across placements).
    """

    def __init__(self, stage_factory: Callable, num_stages: int,
                 n_microbatches: Optional[int] = None, **kwargs):
        super().__init__(**kwargs)
        if num_stages < 1:
            raise ValueError(f"num_stages={num_stages} < 1")
        self.stage_factory = stage_factory
        self.num_stages = num_stages
        self.n_microbatches = n_microbatches
        self.stage = stage_factory()  # template instance: defines the math
        self._warned_fallback = False

    def build(self, rng, input_shape):
        keys = jax.random.split(rng, self.num_stages)
        trees = []
        for i in range(self.num_stages):
            stage = self.stage_factory() if i else self.stage
            if stage.initial_state(input_shape):
                raise ValueError(
                    f"{self.name}: pipeline stages must be stateless")
            p = stage.build(keys[i], input_shape)
            out_shape = stage.output_shape_for(p, {}, input_shape)
            if tuple(out_shape[1:]) != tuple(input_shape[1:]):
                raise ValueError(
                    f"{self.name}: stage must preserve shape, got "
                    f"{tuple(input_shape[1:])} -> {tuple(out_shape[1:])}")
            trees.append(p)
        return jax.tree.map(lambda *ls: jnp.stack(ls), *trees)

    def param_sharding(self, params):
        """Stage dim over ``pipe``; inner dims replicated (composing the
        stage's own model-axis rules inside PP is future work)."""
        return jax.tree.map(lambda _: P(mesh_lib.PIPE_AXIS), params)

    def _stage_fn(self, training):
        def fn(p_stage, h, rng):
            return self.stage.call(p_stage, h, training=training, rng=rng)
        return fn

    def call(self, params, x, *, training=False, rng=None):
        mesh = mesh_lib.global_mesh()
        S = mesh.shape[mesh_lib.PIPE_AXIS]
        fn = self._stage_fn(training)
        # the scan carry must be dtype-stable: enter at the compute dtype the
        # stages will emit (bfloat16 under a mixed-precision policy)
        x = x.astype(compute_dtype())
        if S > 1:
            if self.num_stages % S != 0:
                raise ValueError(
                    f"{self.name}: num_stages={self.num_stages} must be a "
                    f"multiple of the pipe axis size {S}")
            n_micro = self.n_microbatches or S
            dp = mesh.shape[mesh_lib.DATA_AXIS]
            B = x.shape[0]
            # batches the schedule can't split (shape inference's B=1
            # probe, ragged predict tails) run the sequential path — the
            # math is identical, only the chip placement differs
            if B % dp == 0 and (B // dp) % n_micro == 0:
                return gpipe_apply(fn, params, x, mesh=mesh,
                                   n_micro=n_micro, rng=rng,
                                   stages_per_rank=self.num_stages // S)
            if B > dp and not self._warned_fallback:
                # a real batch (not the B=1 probe / tiny tail) losing the
                # pipeline is a silent S-times perf cliff — say so once
                import logging
                logging.getLogger("analytics_zoo_tpu.gpipe").warning(
                    "%s: batch %d (per-shard %s) not divisible by "
                    "n_microbatches=%d — running stages SEQUENTIALLY on the "
                    "pipe=%d mesh; pick a divisible batch size to pipeline",
                    self.name, B, B // dp if B % dp == 0 else f"{B}/{dp}",
                    n_micro, S)
                self._warned_fallback = True
        return sequential_apply(fn, params, x, self.num_stages, rng=rng)
