"""Embedding layers — parity with ``keras/layers/Embedding.scala``,
``SparseEmbedding.scala``, ``WordEmbedding.scala``.

TPU note: embedding lookup compiles to a gather from an HBM-resident table.
``Embedding`` shards the embedding (column) dim over ``model`` so the gather
stays shard-local; :class:`ShardedEmbedding` row-partitions the table instead
and owns the cross-shard merge explicitly (``ops/sharded_embedding.py`` —
dedup'd gathers, sparse scatter-add grads). Plain ``Embedding`` layers can be
upgraded to the sharded engine at step-build time without model-code changes
via ``zoo.embed.sharded`` (``keras/sharded_embed.py``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..engine import Layer, get_initializer, param_dtype


class Embedding(Layer):
    """``Embedding(input_dim, output_dim, init, input_length)`` —
    ``keras/layers/Embedding.scala``. Input int ids (B, T) → (B, T, D).

    Unlike the reference (which 1-indexes ids to match BigDL LookupTable),
    ids here are 0-based."""

    def __init__(self, input_dim: int, output_dim: int, init: str = "uniform",
                 input_length: Optional[int] = None, **kwargs):
        if input_length is not None and "input_shape" not in kwargs:
            kwargs["input_shape"] = (input_length,)
        super().__init__(**kwargs)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.init = init

    def build(self, rng, input_shape):
        w = get_initializer(self.init)(
            rng, (self.input_dim, self.output_dim), param_dtype())
        return {"embeddings": w}

    def param_sharding(self, params):
        """Shard the embedding (column) dim over ``model`` — the gather
        stays local to each shard. When ``output_dim`` doesn't divide by
        the axis size, ``parallel.mesh.param_shardings`` falls back to
        replicating the leaf and says so through its coalesced
        replicated-fallback warning (``analytics_zoo_tpu.mesh``) — the
        degradation is visible, not silent. Rows CAN be split instead:
        :class:`ShardedEmbedding` (or the ``zoo.embed.sharded``
        step-build upgrade, which flips this spec to row partitioning)
        shards the vocab axis with explicit collectives."""
        from jax.sharding import PartitionSpec as P
        from .....parallel.mesh import MODEL_AXIS
        if getattr(self, "_row_shard", False):
            return {"embeddings": P(MODEL_AXIS, None)}
        return {"embeddings": P(None, MODEL_AXIS)}

    def call(self, params, x, *, training=False, rng=None):
        ids = x.astype(jnp.int32)
        return jnp.take(params["embeddings"], ids, axis=0)


class ShardedEmbedding(Embedding):
    """Row-partitioned out-of-core-capable embedding: the ``(V, D)``
    table shards vocab-wise ``P(model, None)`` and the lookup runs
    through ``ops.sharded_embedding.sharded_embedding_lookup`` — dedup'd
    unique-row gathers (each distinct row crosses the interconnect
    once), one explicit psum merge, and a sparse scatter-add VJP whose
    optimizer cost is proportional to touched rows. Drop-in for
    ``Embedding``; on a ``model == 1`` mesh the lookup degrades to the
    unsharded dedup'd gather with identical numerics."""

    def param_sharding(self, params):
        from jax.sharding import PartitionSpec as P
        from .....parallel.mesh import MODEL_AXIS
        return {"embeddings": P(MODEL_AXIS, None)}

    def call(self, params, x, *, training=False, rng=None):
        from .....ops.sharded_embedding import sharded_embedding_lookup
        return sharded_embedding_lookup(params["embeddings"],
                                        x.astype(jnp.int32))


class SparseEmbedding(Layer):
    """``keras/layers/SparseEmbedding.scala`` — multi-hot bag embedding: the
    input is a 0/1 (or weighted) row over the vocab, output is the weighted
    sum of embeddings. On TPU this is just a matmul onto the MXU."""

    def __init__(self, input_dim: int, output_dim: int, init: str = "uniform",
                 combiner: str = "sum", **kwargs):
        super().__init__(**kwargs)
        self.input_dim, self.output_dim = input_dim, output_dim
        self.init = init
        self.combiner = combiner

    def build(self, rng, input_shape):
        w = get_initializer(self.init)(
            rng, (self.input_dim, self.output_dim), param_dtype())
        return {"embeddings": w}

    def call(self, params, x, *, training=False, rng=None):
        y = jnp.matmul(x.astype(params["embeddings"].dtype), params["embeddings"],
                       preferred_element_type=jnp.float32)
        if self.combiner == "mean":
            denom = jnp.maximum(jnp.sum(x, axis=-1, keepdims=True), 1.0)
            y = y / denom
        elif self.combiner == "sqrtn":
            denom = jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=-1, keepdims=True), 1.0))
            y = y / denom
        return y.astype(params["embeddings"].dtype)


class WordEmbedding(Layer):
    """``keras/layers/WordEmbedding.scala`` — embedding initialised from
    pretrained vectors (GloVe in the reference), frozen by default."""

    def __init__(self, weights: np.ndarray, trainable: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.weights = np.asarray(weights)
        self.trainable = trainable

    def build(self, rng, input_shape):
        w = jnp.asarray(self.weights, param_dtype())
        if self.trainable:
            return {"embeddings": w}
        return {}

    def initial_state(self, input_shape):
        if self.trainable:
            return {}
        return {"embeddings": jnp.asarray(self.weights, param_dtype())}

    def apply(self, params, state, x, *, training=False, rng=None):
        table = params["embeddings"] if self.trainable else state["embeddings"]
        return jnp.take(table, x.astype(jnp.int32), axis=0), state
