"""Advanced activation layers — parity with the reference's
``keras/layers/{LeakyReLU,ELU,PReLU,SReLU,ThresholdedReLU,RReLU,Softmax,
HardTanh,HardShrink,SoftShrink,Threshold,BinaryThreshold}.scala`` (all thin
wrappers over BigDL nn modules there; here each is a direct VPU-friendly
elementwise expression XLA fuses into neighbours).

Learnable ones (PReLU, SReLU) carry per-channel parameters like the
reference's defaults.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..engine import Layer, param_dtype

__all__ = ["LeakyReLU", "ELU", "PReLU", "SReLU", "ThresholdedReLU", "RReLU",
           "Softmax", "HardTanh", "HardShrink", "SoftShrink", "Threshold",
           "BinaryThreshold"]


class LeakyReLU(Layer):
    """``LeakyReLU(alpha)``: x if x > 0 else alpha*x."""

    def __init__(self, alpha: float = 0.01, **kwargs):
        super().__init__(**kwargs)
        self.alpha = float(alpha)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > 0, x, self.alpha * x)


class ELU(Layer):
    """``ELU(alpha)``: x if x > 0 else alpha*(exp(x)-1)."""

    def __init__(self, alpha: float = 1.0, **kwargs):
        super().__init__(**kwargs)
        self.alpha = float(alpha)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x))


class PReLU(Layer):
    """``PReLU.scala`` — learnable per-channel negative slope (init 0.25)."""

    def build(self, rng, input_shape):
        ch = input_shape[-1]
        return {"alpha": jnp.full((ch,), 0.25, param_dtype())}

    def call(self, params, x, *, training=False, rng=None):
        a = params["alpha"].astype(x.dtype)
        return jnp.where(x > 0, x, a * x)


class SReLU(Layer):
    """``SReLU.scala`` — s-shaped ReLU with 4 learnable per-channel params:
    y = t_r + a_r(x - t_r) for x >= t_r; x in between; t_l + a_l(x - t_l)
    for x <= t_l."""

    def build(self, rng, input_shape):
        ch = input_shape[-1]
        z = jnp.zeros((ch,), param_dtype())
        return {"t_left": z, "a_left": z,
                "t_right": jnp.ones((ch,), param_dtype()),
                "a_right": jnp.ones((ch,), param_dtype())}

    def call(self, params, x, *, training=False, rng=None):
        tl = params["t_left"].astype(x.dtype)
        al = params["a_left"].astype(x.dtype)
        tr = params["t_right"].astype(x.dtype)
        ar = params["a_right"].astype(x.dtype)
        y = jnp.where(x >= tr, tr + ar * (x - tr), x)
        return jnp.where(x <= tl, tl + al * (x - tl), y)


class ThresholdedReLU(Layer):
    """``ThresholdedReLU(theta)``: x if x > theta else 0."""

    def __init__(self, theta: float = 1.0, **kwargs):
        super().__init__(**kwargs)
        self.theta = float(theta)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > self.theta, x, jnp.zeros_like(x))


class RReLU(Layer):
    """``RReLU(lower, upper)`` — randomized leaky: training samples the
    negative slope ~ U(lower, upper) per element; inference uses the mean."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 **kwargs):
        super().__init__(**kwargs)
        self.lower, self.upper = float(lower), float(upper)

    def call(self, params, x, *, training=False, rng=None):
        if training and rng is not None:
            a = jax.random.uniform(rng, x.shape, x.dtype, self.lower,
                                   self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, a * x)


class Softmax(Layer):
    """``Softmax.scala`` as a standalone layer (last axis)."""

    def call(self, params, x, *, training=False, rng=None):
        return jax.nn.softmax(x, axis=-1)


class HardTanh(Layer):
    """``HardTanh(min_value, max_value)``: clip."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.min_value, self.max_value = float(min_value), float(max_value)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.clip(x, self.min_value, self.max_value)


class HardShrink(Layer):
    """``HardShrink(value)``: x if |x| > value else 0."""

    def __init__(self, value: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.value = float(value)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(jnp.abs(x) > self.value, x, jnp.zeros_like(x))


class SoftShrink(Layer):
    """``SoftShrink(value)``: x -/+ value outside the band, 0 inside."""

    def __init__(self, value: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self.value = float(value)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > self.value, x - self.value,
                         jnp.where(x < -self.value, x + self.value,
                                   jnp.zeros_like(x)))


class Threshold(Layer):
    """``Threshold(th, v)``: x if x > th else v."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.th, self.v = float(th), float(v)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > self.th, x, jnp.full_like(x, self.v))


class BinaryThreshold(Layer):
    """``BinaryThreshold(th)``: 1 where x > th else 0."""

    def __init__(self, th: float = 1e-6, **kwargs):
        super().__init__(**kwargs)
        self.th = float(th)

    def call(self, params, x, *, training=False, rng=None):
        return (x > self.th).astype(x.dtype)
