"""Convolution layers — parity with the reference's Keras-1 conv family
(``pipeline/api/keras/layers/``: Convolution1D.scala, Convolution2D.scala,
AtrousConvolution1D/2D.scala, SeparableConvolution2D.scala,
Deconvolution2D.scala, ZeroPadding*.scala, Cropping*.scala, UpSampling*.scala).

TPU-native design: all convs run channels-last (NHWC/NWC) through
``lax.conv_general_dilated`` so XLA tiles them straight onto the MXU — the
reference's default NCHW (``dim_ordering="th"``) is a CPU/MKL layout and is
deliberately not carried over. Accumulation is float32 regardless of the
compute dtype (bfloat16 inputs keep full MXU rate).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..engine import Layer, compute_dtype, get_initializer, param_dtype
from .core import get_activation


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _padding(border_mode: str):
    if border_mode not in ("valid", "same"):
        raise ValueError(f"border_mode must be 'valid' or 'same', got {border_mode!r}")
    return border_mode.upper()


class Convolution1D(Layer):
    """``Convolution1D(nb_filter, filter_length, activation, border_mode,
    subsample_length)`` — Convolution1D.scala. Input (B, T, C) → (B, T', F)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 init: str = "glorot_uniform", activation=None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 dilation_rate: int = 1, bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.init = init
        self.activation = get_activation(activation)
        self.border_mode = border_mode
        self.subsample_length = subsample_length
        self.dilation_rate = dilation_rate
        self.bias = bias

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        p = {"W": get_initializer(self.init)(
            rng, (self.filter_length, in_ch, self.nb_filter), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = lax.conv_general_dilated(
            x.astype(cd), params["W"].astype(cd),
            window_strides=(self.subsample_length,),
            padding=_padding(self.border_mode),
            rhs_dilation=(self.dilation_rate,),
            dimension_numbers=("NWC", "WIO", "NWC"),
            preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y


class AtrousConvolution1D(Convolution1D):
    """``AtrousConvolution1D.scala`` — dilated 1D conv."""

    def __init__(self, nb_filter: int, filter_length: int,
                 atrous_rate: int = 1, **kwargs):
        super().__init__(nb_filter, filter_length,
                         dilation_rate=atrous_rate, **kwargs)


class Convolution2D(Layer):
    """``Convolution2D(nb_filter, nb_row, nb_col, activation, border_mode,
    subsample)`` — Convolution2D.scala. Input (B, H, W, C) → (B, H', W', F).
    (Channels-last; the reference's NCHW maps to NHWC on TPU.)"""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init: str = "glorot_uniform", activation=None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 dilation: Tuple[int, int] = (1, 1), bias: bool = True,
                 **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.init = init
        self.activation = get_activation(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.dilation = _pair(dilation)
        self.bias = bias

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        p = {"W": get_initializer(self.init)(
            rng, (self.nb_row, self.nb_col, in_ch, self.nb_filter),
            param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = lax.conv_general_dilated(
            x.astype(cd), params["W"].astype(cd),
            window_strides=self.subsample,
            padding=_padding(self.border_mode),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y

    def quantized_call(self, qp, x):
        """Static int8 conv (inference runtime): calibrated activation scale,
        int8 x int8 -> int32 accumulation on the MXU, fused per-channel
        rescale — the OpenVINO-calibrated-int8 replacement (SURVEY §2.3)."""
        xq = jnp.clip(jnp.round(x / qp["x_scale"]), -127, 127).astype(jnp.int8)
        y = lax.conv_general_dilated(
            xq, qp["W"],
            window_strides=self.subsample,
            padding=_padding(self.border_mode),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
        y = y.astype(jnp.float32) * (qp["x_scale"] * qp["w_scale"])
        if self.bias:
            y = y + qp["b"]
        if self.activation is not None:
            y = self.activation(y)
        return y


class AtrousConvolution2D(Convolution2D):
    """``AtrousConvolution2D.scala`` — dilated 2D conv."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 atrous_rate: Tuple[int, int] = (1, 1), **kwargs):
        super().__init__(nb_filter, nb_row, nb_col, dilation=atrous_rate,
                         **kwargs)


def _depthwise_apply(x, w, strides, border_mode):
    """Shared depthwise conv core (per-channel grouped conv, NHWC)."""
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=_padding(border_mode),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=x.shape[-1],
        preferred_element_type=jnp.float32)


class SeparableConvolution2D(Layer):
    """``SeparableConvolution2D.scala`` — depthwise conv (per-channel,
    ``feature_group_count``) followed by a 1x1 pointwise conv."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init: str = "glorot_uniform", activation=None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 depth_multiplier: int = 1, bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_row, self.nb_col = nb_row, nb_col
        self.init = init
        self.activation = get_activation(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.depth_multiplier = depth_multiplier
        self.bias = bias

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        ini = get_initializer(self.init)
        p = {"depthwise": ini(k1, (self.nb_row, self.nb_col, 1,
                                   in_ch * self.depth_multiplier),
                              param_dtype()),
             "pointwise": ini(k2, (1, 1, in_ch * self.depth_multiplier,
                                   self.nb_filter), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = _depthwise_apply(x.astype(cd), params["depthwise"].astype(cd),
                             self.subsample, self.border_mode).astype(cd)
        y = lax.conv_general_dilated(
            y, params["pointwise"].astype(cd), window_strides=(1, 1),
            padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y


class DepthwiseConvolution2D(Layer):
    """Standalone depthwise conv (one filter stack per input channel,
    ``feature_group_count=in_ch``) — the building block MobileNet-style
    topologies interleave with BatchNorm, which the fused
    :class:`SeparableConvolution2D` can't express."""

    def __init__(self, nb_row: int, nb_col: int,
                 init: str = "glorot_uniform", activation=None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 depth_multiplier: int = 1, bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_row, self.nb_col = nb_row, nb_col
        self.init = init
        self.activation = get_activation(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.depth_multiplier = depth_multiplier
        self.bias = bias

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        p = {"depthwise": get_initializer(self.init)(
            rng, (self.nb_row, self.nb_col, 1,
                  in_ch * self.depth_multiplier), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((in_ch * self.depth_multiplier,),
                               param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = _depthwise_apply(x.astype(cd), params["depthwise"].astype(cd),
                             self.subsample, self.border_mode).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y


class Deconvolution2D(Layer):
    """``Deconvolution2D.scala`` — transposed conv (stride-upsampling)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init: str = "glorot_uniform", activation=None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True,
                 **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.nb_row, self.nb_col = nb_row, nb_col
        self.init = init
        self.activation = get_activation(activation)
        self.subsample = _pair(subsample)
        self.bias = bias

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        p = {"W": get_initializer(self.init)(
            rng, (self.nb_row, self.nb_col, in_ch, self.nb_filter),
            param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = lax.conv_transpose(
            x.astype(cd), params["W"].astype(cd),
            strides=self.subsample, padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y


class ZeroPadding1D(Layer):
    """``ZeroPadding1D.scala`` — pad the time axis."""

    def __init__(self, padding: Union[int, Tuple[int, int]] = 1, **kwargs):
        super().__init__(**kwargs)
        self.padding = _pair(padding)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.pad(x, ((0, 0), self.padding, (0, 0)))


class ZeroPadding2D(Layer):
    """``ZeroPadding2D.scala`` — pad height/width."""

    def __init__(self, padding: Tuple[int, int] = (1, 1), **kwargs):
        super().__init__(**kwargs)
        self.padding = _pair(padding)

    def call(self, params, x, *, training=False, rng=None):
        ph, pw = self.padding
        return jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))


class Cropping1D(Layer):
    """``Cropping1D.scala``."""

    def __init__(self, cropping: Tuple[int, int] = (1, 1), **kwargs):
        super().__init__(**kwargs)
        self.cropping = _pair(cropping)

    def call(self, params, x, *, training=False, rng=None):
        a, b = self.cropping
        return x[:, a:x.shape[1] - b, :]


class Cropping2D(Layer):
    """``Cropping2D.scala``."""

    def __init__(self, cropping=((0, 0), (0, 0)), **kwargs):
        super().__init__(**kwargs)
        self.cropping = (tuple(cropping[0]), tuple(cropping[1]))

    def call(self, params, x, *, training=False, rng=None):
        (t, b), (l, r) = self.cropping
        return x[:, t:x.shape[1] - b, l:x.shape[2] - r, :]


class UpSampling1D(Layer):
    """``UpSampling1D.scala`` — repeat timesteps."""

    def __init__(self, length: int = 2, **kwargs):
        super().__init__(**kwargs)
        self.length = length

    def call(self, params, x, *, training=False, rng=None):
        return jnp.repeat(x, self.length, axis=1)


class UpSampling2D(Layer):
    """``UpSampling2D.scala`` — nearest-neighbour spatial upsampling."""

    def __init__(self, size: Tuple[int, int] = (2, 2), **kwargs):
        super().__init__(**kwargs)
        self.size = _pair(size)

    def call(self, params, x, *, training=False, rng=None):
        y = jnp.repeat(x, self.size[0], axis=1)
        return jnp.repeat(y, self.size[1], axis=2)


class LocallyConnected1D(Layer):
    """``LocallyConnected1D.scala`` — unshared conv: one filter per output
    position. Implemented as a batched matmul over unfolded patches (MXU-
    friendly einsum, no Python loop)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 init: str = "glorot_uniform", activation=None,
                 subsample_length: int = 1, bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.init = init
        self.activation = get_activation(activation)
        self.subsample_length = subsample_length
        self.bias = bias

    def _out_len(self, t: int) -> int:
        return (t - self.filter_length) // self.subsample_length + 1

    def build(self, rng, input_shape):
        t, c = input_shape[1], input_shape[2]
        out_t = self._out_len(t)
        p = {"W": get_initializer(self.init)(
            rng, (out_t, self.filter_length * c, self.nb_filter),
            param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((out_t, self.nb_filter), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        out_t = self._out_len(x.shape[1])
        # unfold patches: (B, out_t, filter_length * C)
        idx = (jnp.arange(out_t)[:, None] * self.subsample_length
               + jnp.arange(self.filter_length)[None, :])
        patches = x[:, idx, :].reshape(x.shape[0], out_t, -1)
        y = jnp.einsum("btk,tkf->btf", patches.astype(cd),
                       params["W"].astype(cd),
                       preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y


class ShareConvolution2D(Convolution2D):
    """``ShareConvolution2D.scala`` — the reference variant whose weight
    buffers are shared across replicas (a JVM memory concern); functionally a
    ``Convolution2D`` with explicit pad_h/pad_w, which is all that survives
    the functional re-design (params are immutable pytrees — sharing is the
    default, XLA donates/aliases buffers)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 pad_h: int = 0, pad_w: int = 0, **kwargs):
        super().__init__(nb_filter, nb_row, nb_col, **kwargs)
        self.pad_h, self.pad_w = int(pad_h), int(pad_w)

    def call(self, params, x, *, training=False, rng=None):
        if self.pad_h or self.pad_w:
            x = jnp.pad(x, ((0, 0), (self.pad_h, self.pad_h),
                            (self.pad_w, self.pad_w), (0, 0)))
        return super().call(params, x, training=training, rng=rng)


class SeparableConvolution1D(Layer):
    """``SeparableConvolution1D.scala`` — depthwise temporal conv
    (per-channel, ``feature_group_count``) followed by a pointwise 1x1 over
    (B, T, C)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 init: str = "glorot_uniform", activation=None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 depth_multiplier: int = 1, bias: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.nb_filter = nb_filter
        self.filter_length = int(filter_length)
        self.init = init
        self.activation = get_activation(activation)
        self.border_mode = border_mode
        self.subsample_length = int(subsample_length)
        self.depth_multiplier = int(depth_multiplier)
        self.bias = bias

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        ini = get_initializer(self.init)
        p = {"depthwise": ini(k1, (self.filter_length, 1,
                                   in_ch * self.depth_multiplier),
                              param_dtype()),
             "pointwise": ini(k2, (1, in_ch * self.depth_multiplier,
                                   self.nb_filter), param_dtype())}
        if self.bias:
            p["b"] = jnp.zeros((self.nb_filter,), param_dtype())
        return p

    def call(self, params, x, *, training=False, rng=None):
        cd = compute_dtype()
        y = lax.conv_general_dilated(
            x.astype(cd), params["depthwise"].astype(cd),
            window_strides=(self.subsample_length,),
            padding=_padding(self.border_mode),
            dimension_numbers=("NWC", "WIO", "NWC"),
            feature_group_count=x.shape[-1],
            preferred_element_type=jnp.float32).astype(cd)
        y = lax.conv_general_dilated(
            y, params["pointwise"].astype(cd), window_strides=(1,),
            padding="VALID", dimension_numbers=("NWC", "WIO", "NWC"),
            preferred_element_type=jnp.float32).astype(cd)
        if self.bias:
            y = y + params["b"].astype(cd)
        if self.activation is not None:
            y = self.activation(y)
        return y
