"""Normalization layers — parity with ``keras/layers/BatchNormalization.scala``
and ``keras/layers/LayerNorm.scala``.

BatchNorm carries its moving statistics as non-trainable *state* threaded
functionally through ``apply`` (no mutation — jit/shard safe). Under data
parallelism the batch-axis reduction runs *inside* the sharded program, so
XLA's SPMD partitioner turns it into a global (all-reduced) mean/var — i.e.
sync-BatchNorm: statistics are identical for dp=1 and dp=N (asserted by
``tests/test_layers.py::test_batchnorm_dp_invariant``). This is a deliberate
improvement over the reference, whose per-replica modules keep local stats
(``Topology.scala:1150-1158``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..engine import Layer, param_dtype


class BatchNormalization(Layer):
    """``BatchNormalization(epsilon, momentum, beta_init, gamma_init,
    dim_ordering)`` — normalizes the channel axis (last axis here; the
    reference's default NCHW maps to NHWC on TPU, where channels-last is the
    layout XLA tiles best)."""

    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 axis: int = -1, scale: bool = True, center: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.epsilon = epsilon
        self.momentum = momentum
        self.axis = axis
        self.scale = scale
        self.center = center

    def _dim(self, input_shape):
        return input_shape[self.axis]

    def build(self, rng, input_shape):
        d = self._dim(input_shape)
        p = {}
        if self.scale:
            p["gamma"] = jnp.ones((d,), param_dtype())
        if self.center:
            p["beta"] = jnp.zeros((d,), param_dtype())
        return p

    def initial_state(self, input_shape):
        d = self._dim(input_shape)
        return {
            "moving_mean": jnp.zeros((d,), jnp.float32),
            "moving_var": jnp.ones((d,), jnp.float32),
        }

    def apply(self, params, state, x, *, training=False, rng=None):
        axis = x.ndim + self.axis if self.axis < 0 else self.axis
        reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
        # broadcast (d,)-shaped stats/params against the normalized axis, not
        # blindly against the last axis — axis=1 on (B, C, L) must work
        bshape = tuple(x.shape[axis] if i == axis else 1 for i in range(x.ndim))
        if training:
            mean = jnp.mean(x.astype(jnp.float32), axis=reduce_axes)
            var = jnp.var(x.astype(jnp.float32), axis=reduce_axes)
            m = self.momentum
            new_state = {
                "moving_mean": m * state["moving_mean"] + (1 - m) * mean,
                "moving_var": m * state["moving_var"] + (1 - m) * var,
            }
        else:
            mean, var = state["moving_mean"], state["moving_var"]
            new_state = state
        inv = jax.lax.rsqrt(var + self.epsilon)
        y = (x - mean.astype(x.dtype).reshape(bshape)) \
            * inv.astype(x.dtype).reshape(bshape)
        if self.scale:
            y = y * params["gamma"].astype(x.dtype).reshape(bshape)
        if self.center:
            y = y + params["beta"].astype(x.dtype).reshape(bshape)
        return y, new_state


class LayerNorm(Layer):
    """``keras/layers/LayerNorm.scala`` — normalize over the last axis."""

    def __init__(self, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.epsilon = epsilon

    def build(self, rng, input_shape):
        d = input_shape[-1]
        return {"gamma": jnp.ones((d,), param_dtype()),
                "beta": jnp.zeros((d,), param_dtype())}

    def call(self, params, x, *, training=False, rng=None):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        y = y * params["gamma"] + params["beta"]
        return y.astype(x.dtype)


class L2Normalize(Layer):
    """autograd ``l2Normalize`` as a layer (``autograd/math.scala``)."""

    def __init__(self, axis: int = -1, epsilon: float = 1e-12, **kwargs):
        super().__init__(**kwargs)
        self.axis, self.epsilon = axis, epsilon

    def call(self, params, x, *, training=False, rng=None):
        norm = jnp.sqrt(jnp.sum(x * x, axis=self.axis, keepdims=True) + self.epsilon)
        return x / norm
