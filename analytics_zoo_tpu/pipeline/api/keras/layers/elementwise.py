"""Elementwise / tensor-op layers — parity with the reference's
``keras/layers/{AddConstant,MulConstant,Negative,Power,Exp,Log,Sqrt,Square,
Mul,CAdd,CMul,Scale,Max,Expand,GaussianSampler,ResizeBilinear}.scala``.
Dim conventions follow the package's Select/Squeeze style: 0 = batch,
negatives allowed.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..engine import Layer, param_dtype

__all__ = ["AddConstant", "MulConstant", "Negative", "Power", "Exp", "Log",
           "Sqrt", "Square", "Mul", "CAdd", "CMul", "Scale", "Max",
           "Expand", "GaussianSampler", "ResizeBilinear"]


class AddConstant(Layer):
    def __init__(self, constant: float, **kwargs):
        super().__init__(**kwargs)
        self.constant = float(constant)

    def call(self, params, x, *, training=False, rng=None):
        return x + self.constant


class MulConstant(Layer):
    def __init__(self, constant: float, **kwargs):
        super().__init__(**kwargs)
        self.constant = float(constant)

    def call(self, params, x, *, training=False, rng=None):
        return x * self.constant


class Negative(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return -x


class Power(Layer):
    """``Power(power, scale, shift)``: (shift + scale * x) ** power."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.power, self.scale, self.shift = (float(power), float(scale),
                                              float(shift))

    def call(self, params, x, *, training=False, rng=None):
        return jnp.power(self.shift + self.scale * x, self.power)


class Exp(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.exp(x)


class Log(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.log(x)


class Sqrt(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.sqrt(x)


class Square(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return jnp.square(x)


class Mul(Layer):
    """``Mul.scala`` — ONE learnable scalar multiplier."""

    def build(self, rng, input_shape):
        return {"w": jnp.ones((1,), param_dtype())}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["w"].astype(x.dtype)


class CAdd(Layer):
    """``CAdd(size)`` — learnable bias of ``size``, broadcast-added."""

    def __init__(self, size: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"bias": jnp.zeros(self.size, param_dtype())}

    def call(self, params, x, *, training=False, rng=None):
        return x + params["bias"].astype(x.dtype)


class CMul(Layer):
    """``CMul(size)`` — learnable scale of ``size``, broadcast-multiplied."""

    def __init__(self, size: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size, param_dtype())}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["weight"].astype(x.dtype)


class Scale(Layer):
    """``Scale(size)`` — CMul then CAdd (affine per broadcastable block).
    ``init_weight`` sets the initial multiplier (e.g. SSD's conv4_3 norm
    scale starts at 20)."""

    def __init__(self, size: Sequence[int], init_weight: float = 1.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.size = tuple(int(s) for s in size)
        self.init_weight = float(init_weight)

    def build(self, rng, input_shape):
        return {"weight": jnp.full(self.size, self.init_weight,
                                   param_dtype()),
                "bias": jnp.zeros(self.size, param_dtype())}

    def call(self, params, x, *, training=False, rng=None):
        return (x * params["weight"].astype(x.dtype)
                + params["bias"].astype(x.dtype))


class Max(Layer):
    """``Max(dim)`` — max-reduce one axis (0 = batch, like Select)."""

    def __init__(self, dim: int, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

    def call(self, params, x, *, training=False, rng=None):
        return jnp.max(x, axis=self.dim)


class Expand(Layer):
    """``Expand`` — broadcast singleton dims up to ``shape`` (sans batch)."""

    def __init__(self, shape: Sequence[int], **kwargs):
        super().__init__(**kwargs)
        self.shape = tuple(int(s) for s in shape)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.broadcast_to(x, (x.shape[0],) + self.shape)


class GaussianSampler(Layer):
    """``GaussianSampler.scala`` — the VAE reparameterization: input
    ``[mean, log_var]`` → mean + exp(log_var/2) * eps. Deterministic (mean)
    when no rng is supplied (inference)."""

    def call(self, params, x, *, training=False, rng=None):
        mean, log_var = x
        if rng is None:
            return mean
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(0.5 * log_var) * eps


class ResizeBilinear(Layer):
    """``ResizeBilinear(output_height, output_width)`` — channels-last
    bilinear resize (``jax.image.resize``, align_corners=False semantics)."""

    def __init__(self, output_height: int, output_width: int, **kwargs):
        super().__init__(**kwargs)
        self.output_height = int(output_height)
        self.output_width = int(output_width)

    def call(self, params, x, *, training=False, rng=None):
        b, _, _, c = x.shape
        return jax.image.resize(
            x, (b, self.output_height, self.output_width, c), "bilinear")
