"""Recurrent layers — parity with the reference's Keras-1 RNN family
(``pipeline/api/keras/layers/``: SimpleRNN.scala, LSTM.scala, GRU.scala,
Bidirectional.scala; BigDL ``Recurrent`` containers underneath).

TPU-native design: the time loop is ONE ``lax.scan`` over the sequence axis —
a single compiled loop whose per-step body is a fused (B, D) x (D, 4U) matmul
on the MXU. The input projection ``x @ W`` for all timesteps is hoisted out of
the scan as one big (B*T, D) x (D, 4U) matmul, so the recurrent loop only
carries the (U, 4U) recurrence — the standard XLA RNN recipe, unlike the
reference's per-timestep BigDL cell graph.

Weight layout follows Keras-1 exactly (gate order i, f, c, o for LSTM;
z, r, h for GRU; reset gate applied BEFORE the recurrent matmul), so golden
tests can compare against independent oracles.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..engine import Layer, compute_dtype, get_initializer, param_dtype
from .core import get_activation


class _RecurrentBase(Layer):
    """Shared plumbing: shapes, scan driver, return_sequences/go_backwards."""

    def __init__(self, output_dim: int, activation="tanh",
                 init: str = "glorot_uniform", inner_init: str = "orthogonal",
                 return_sequences: bool = False, go_backwards: bool = False,
                 **kwargs):
        super().__init__(**kwargs)
        self.output_dim = output_dim
        self.activation = get_activation(activation)
        self.init = init
        self.inner_init = inner_init
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    # subclasses define: n_gates, step(params, carry, zx) -> (carry, h)
    n_gates = 1

    def build(self, rng, input_shape):
        d = input_shape[-1]
        u = self.output_dim
        k1, k2 = jax.random.split(rng)
        return {
            "W": get_initializer(self.init)(k1, (d, self.n_gates * u),
                                            param_dtype()),
            "U": get_initializer(self.inner_init)(k2, (u, self.n_gates * u),
                                                  param_dtype()),
            "b": jnp.zeros((self.n_gates * u,), param_dtype()),
        }

    def initial_carry(self, batch: int, dtype):
        return jnp.zeros((batch, self.output_dim), dtype)

    def run(self, params, x, carry0=None):
        """Full scan: returns (hidden sequence (B, T, U), final carry).
        ``carry0`` lets a decoder start from bridged encoder states
        (``Seq2seq.scala`` / ``RNNDecoder.scala``)."""
        cd = compute_dtype()
        x = x.astype(cd)
        b, t, _ = x.shape
        if self.go_backwards:
            x = x[:, ::-1, :]
        # hoist the input projection out of the loop: one (B*T, D) matmul
        zx = (jnp.einsum("btd,dk->btk", x, params["W"].astype(cd),
                         preferred_element_type=jnp.float32)
              + params["b"].astype(jnp.float32))
        zx = jnp.swapaxes(zx, 0, 1)  # (T, B, n_gates*U) scan over time
        if carry0 is None:
            carry0 = self.initial_carry(b, jnp.float32)
        U = params["U"].astype(cd)

        def body(carry, z_t):
            return self.step(U, carry, z_t)

        final_carry, hs = lax.scan(body, carry0, zx)
        hs = jnp.swapaxes(hs, 0, 1).astype(cd)  # (B, T, U)
        return hs, final_carry

    def call(self, params, x, *, training=False, rng=None):
        hs, _ = self.run(params, x)
        if self.return_sequences:
            return hs[:, ::-1, :] if self.go_backwards else hs
        return hs[:, -1, :]


class SimpleRNN(_RecurrentBase):
    """``SimpleRNN.scala`` — h_t = act(x_t W + h_{t-1} U + b)."""

    n_gates = 1

    def step(self, U, h, z_t):
        h = self.activation(z_t + h @ U)
        return h, h


class LSTM(_RecurrentBase):
    """``LSTM.scala`` — Keras-1 gates (i, f, c, o):
    i = σ(z_i), f = σ(z_f), c = f*c' + i*tanh(z_c), o = σ(z_o),
    h = o * act(c), where z = x W + h' U + b."""

    n_gates = 4

    def __init__(self, output_dim: int, activation="tanh",
                 inner_activation="hard_sigmoid", **kwargs):
        super().__init__(output_dim, activation=activation, **kwargs)
        self.inner_activation = get_activation(inner_activation)

    def initial_carry(self, batch: int, dtype):
        z = jnp.zeros((batch, self.output_dim), dtype)
        return (z, z)  # (h, c)

    def step(self, U, carry, z_t):
        h_prev, c_prev = carry
        u = self.output_dim
        z = z_t + h_prev @ U
        i = self.inner_activation(z[:, :u])
        f = self.inner_activation(z[:, u:2 * u])
        g = jnp.tanh(z[:, 2 * u:3 * u])
        o = self.inner_activation(z[:, 3 * u:])
        c = f * c_prev + i * g
        h = o * self.activation(c)
        return (h, c), h


class GRU(_RecurrentBase):
    """``GRU.scala`` — Keras-1 gates (z, r, h), reset BEFORE the recurrent
    matmul: hh = act(x W_h + (r*h') U_h + b_h); h = z*h' + (1-z)*hh."""

    n_gates = 3

    def __init__(self, output_dim: int, activation="tanh",
                 inner_activation="hard_sigmoid", **kwargs):
        super().__init__(output_dim, activation=activation, **kwargs)
        self.inner_activation = get_activation(inner_activation)

    def step(self, U, h_prev, z_t):
        u = self.output_dim
        rec = h_prev @ U[:, :2 * u]
        z = self.inner_activation(z_t[:, :u] + rec[:, :u])
        r = self.inner_activation(z_t[:, u:2 * u] + rec[:, u:])
        hh = self.activation(z_t[:, 2 * u:] + (r * h_prev) @ U[:, 2 * u:])
        h = z * h_prev + (1.0 - z) * hh
        return h, h


class Bidirectional(Layer):
    """``Bidirectional.scala`` — run a recurrent layer forward and (a fresh
    copy) backward, merging outputs (concat/sum/mul/ave)."""

    def __init__(self, layer: _RecurrentBase, merge_mode: str = "concat",
                 **kwargs):
        super().__init__(**kwargs)
        import copy
        self.forward = layer
        self.backward = copy.copy(layer)
        self.backward._auto_name = False
        self.backward.name = layer.name + "_bwd"
        self.backward.go_backwards = not layer.go_backwards
        self.merge_mode = merge_mode

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        return {"forward": self.forward.build(k1, input_shape),
                "backward": self.backward.build(k2, input_shape)}

    def call(self, params, x, *, training=False, rng=None):
        yf = self.forward.call(params["forward"], x, training=training, rng=rng)
        yb = self.backward.call(params["backward"], x, training=training, rng=rng)
        m = self.merge_mode
        if m == "concat":
            return jnp.concatenate([yf, yb], axis=-1)
        if m == "sum":
            return yf + yb
        if m == "mul":
            return yf * yb
        if m == "ave":
            return (yf + yb) / 2.0
        raise ValueError(f"unknown merge_mode {m!r}")
