"""Sparse Mixture-of-Experts — the layer that makes the ``expert`` mesh axis
real (SURVEY §2.4: EP is "absent in the reference; greenfield").

The reference has no MoE (`pipeline/api/keras/layers/` contains none), so this
is designed TPU-first rather than mirrored: the GShard einsum formulation —
capacity-bounded token dispatch expressed as one-hot matmuls — keeps every
shape static for XLA and puts the FLOPs on the MXU, and the expert-stacked
weight tensors ``(E, d_in, d_h)`` shard over the ``expert`` mesh axis (their
hidden dim can additionally shard over ``model``), so GSPMD inserts the
dispatch/combine all-to-alls over ICI.

Auxiliary losses (load-balance + router z-loss) ride the layer-state channel:
``apply`` returns them under the reserved state key ``aux_loss``, which the
training loop adds to the task loss *inside* the differentiated function —
see ``training.py`` ``_aux_loss_sum`` — so the router receives gradient.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..engine import Layer, compute_dtype, get_initializer, param_dtype
from .core import get_activation


class SparseMoE(Layer):
    """Token-choice top-k sparse MoE with expert capacity.

    Each token's router picks its ``top_k`` experts out of ``num_experts``;
    every expert processes at most ``capacity`` tokens per batch
    (``capacity = ceil(top_k * n_tokens / num_experts) * capacity_factor``),
    overflow tokens are dropped (contribute zero — pair with a residual
    connection, as in Switch/GShard). Input ``(B, d)`` or ``(B, T, d)``;
    output has ``output_dim`` features (default: same as input).

    The load-balance loss is the Switch-Transformer form
    ``E * dot(frac_tokens_per_expert, mean_router_prob)`` scaled by
    ``aux_loss_weight``; ``router_z_weight`` optionally adds the ST-MoE
    z-loss ``mean(logsumexp(logits)^2)`` to keep router logits small.
    """

    def __init__(self, num_experts: int, hidden_dim: int,
                 output_dim: Optional[int] = None, top_k: int = 2,
                 capacity_factor: float = 1.25, activation="relu",
                 aux_loss_weight: float = 1e-2, router_z_weight: float = 0.0,
                 router_noise: float = 0.0, init: str = "glorot_uniform",
                 **kwargs):
        super().__init__(**kwargs)
        if not 1 <= top_k <= num_experts:
            raise ValueError(f"top_k={top_k} not in [1, {num_experts}]")
        self.num_experts = num_experts
        self.hidden_dim = hidden_dim
        self.output_dim = output_dim
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = get_activation(activation)
        self.aux_loss_weight = aux_loss_weight
        self.router_z_weight = router_z_weight
        self.router_noise = router_noise
        self.init = init

    def build(self, rng, input_shape):
        d = input_shape[-1]
        out = self.output_dim or d
        E, h = self.num_experts, self.hidden_dim
        init = get_initializer(self.init)
        k = jax.random.split(rng, 3)
        return {
            # router kept in the param dtype; routing math runs in f32
            "Wg": init(k[0], (d, E), param_dtype()),
            "W1": init(k[1], (E, d, h), param_dtype()),
            "b1": jnp.zeros((E, h), param_dtype()),
            "W2": init(k[2], (E, h, out), param_dtype()),
            "b2": jnp.zeros((E, out), param_dtype()),
        }

    def initial_state(self, input_shape):
        return {"aux_loss": jnp.zeros((), jnp.float32)}

    def param_sharding(self, params):
        """Expert-stacked weights shard over the ``expert`` axis; their
        hidden dim additionally over ``model`` (EP x TP). The router stays
        replicated — every token needs all expert scores."""
        from jax.sharding import PartitionSpec as P
        from .....parallel.mesh import EXPERT_AXIS, MODEL_AXIS
        return {
            "Wg": None,
            "W1": P(EXPERT_AXIS, None, MODEL_AXIS),
            "b1": P(EXPERT_AXIS, MODEL_AXIS),
            "W2": P(EXPERT_AXIS, MODEL_AXIS, None),
            "b2": P(EXPERT_AXIS, None),
        }

    # -- routing ------------------------------------------------------------
    def _route(self, logits):
        """Top-k gates + capacity-bounded positions, all static shapes.

        Returns ``(dispatch, combine, aux)``: dispatch ``(N, E, C)`` is the
        0/1 token->(expert, slot) assignment, combine is dispatch weighted by
        the renormalized gate values."""
        N, E = logits.shape
        k = self.top_k
        cap = max(1, int(-(-k * N // E) * self.capacity_factor))
        cap = min(cap, N)

        probs = jax.nn.softmax(logits, axis=-1)              # (N, E) f32
        gate_vals, idx = jax.lax.top_k(probs, k)             # (N, k)
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # (k, N, E) one-hot choices; choice rank 0 has dispatch priority —
        # positions count choice-0 tokens before any choice-1 token, so a
        # token's primary expert is the last to drop it under overflow
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32).transpose(1, 0, 2)
        flat = mask.reshape(k * N, E)
        pos_flat = jnp.cumsum(flat, axis=0) - flat           # (k*N, E)
        pos = (pos_flat.reshape(k, N, E) * mask).sum(-1).astype(jnp.int32)
        kept = mask * (pos_flat < cap).reshape(k, N, E)      # (k, N, E)

        slot = jax.nn.one_hot(pos, cap, dtype=jnp.float32)   # (k, N, C)
        assign = kept[..., None] * slot[:, :, None, :]       # (k, N, E, C)
        dispatch = assign.sum(0)                             # (N, E, C)
        combine = (assign * gate_vals.T[..., None, None]).sum(0)

        # Switch load-balance loss on the primary choice + optional z-loss
        frac_tokens = mask[0].mean(0)                        # (E,)
        frac_probs = probs.mean(0)
        aux = self.aux_loss_weight * E * jnp.dot(frac_tokens, frac_probs)
        if self.router_z_weight:
            z = jax.scipy.special.logsumexp(logits, axis=-1)
            aux = aux + self.router_z_weight * jnp.mean(z * z)
        return dispatch, combine, aux.astype(jnp.float32)

    def _expert_constraint(self, a, spec):
        """Pin the per-expert tensors to the ``expert`` axis when one exists,
        forcing GSPMD to place the dispatch/combine all-to-all here."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .....parallel.mesh import EXPERT_AXIS, global_mesh
        mesh = global_mesh()
        if (mesh.shape[EXPERT_AXIS] > 1
                and self.num_experts % mesh.shape[EXPERT_AXIS] == 0):
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(EXPERT_AXIS, *spec)))
        return a

    def apply(self, params, state, x, *, training=False, rng=None):
        cd = compute_dtype()
        lead = x.shape[:-1]
        d = x.shape[-1]
        tokens = x.reshape(-1, d)
        N = tokens.shape[0]

        logits = jnp.matmul(tokens.astype(jnp.float32),
                            params["Wg"].astype(jnp.float32))
        if training and self.router_noise > 0.0:
            if rng is None:
                raise ValueError(f"{self.name}: router noise needs an rng")
            logits = logits * jax.random.uniform(
                rng, logits.shape, minval=1.0 - self.router_noise,
                maxval=1.0 + self.router_noise)
        dispatch, combine, aux = self._route(logits)

        xin = jnp.einsum("nec,nd->ecd", dispatch.astype(cd),
                         tokens.astype(cd),
                         preferred_element_type=jnp.float32).astype(cd)
        xin = self._expert_constraint(xin, (None, None))
        h = jnp.einsum("ecd,edh->ech", xin, params["W1"].astype(cd),
                       preferred_element_type=jnp.float32).astype(cd)
        h = self.activation(h + params["b1"].astype(cd)[:, None, :])
        out = jnp.einsum("ech,eho->eco", h, params["W2"].astype(cd),
                         preferred_element_type=jnp.float32).astype(cd)
        out = out + params["b2"].astype(cd)[:, None, :]
        out = self._expert_constraint(out, (None, None))
        y = jnp.einsum("nec,eco->no", combine.astype(cd), out,
                       preferred_element_type=jnp.float32).astype(cd)
        return y.reshape(*lead, y.shape[-1]), {"aux_loss": aux}

    def call(self, params, x, *, training=False, rng=None):
        y, _ = self.apply(params, {}, x, training=training, rng=rng)
        return y
