"""Fused LM-head loss resolution — wires ``ops/fused_cross_entropy`` into
the training loop without touching model code.

``resolve_fused_loss(model, loss_fn)`` recognizes the (head Dense, sparse-CE
loss) pattern at step-build time and returns a spec that computes the loss
directly from the head's INPUT hidden states: the trunk runs normally, the
head layer's container dispatch is intercepted to identity
(``engine.intercept_layer_calls`` — the same hook the int8 inference runtime
uses), and the fused blockwise loss consumes the head's ``W``/``b`` params
straight from the param tree, so the ``(B·T, V)`` logits tensor is never
materialized in the training step. Gradients to the head weights flow
through the fused custom VJP; everything upstream is untouched.

Recognized patterns (``zoo.train.fused_ce``: auto | true | false):

* loss ``scce_with_logits`` + a linear head ``Dense(V)`` — exact fusion;
* loss ``scce`` + a ``Dense(V, activation="softmax")`` head — the fused
  logits-form objective, numerically the exact cross-entropy the clipped
  probability form approximates (equivalence-tested in
  ``tests/test_fused_ce.py``). EXPLICIT ``zoo.train.fused_ce=true``
  only: the probability form's eps-clip makes saturated-regime losses
  differ, so ``auto`` never silently substitutes this pattern.

``auto`` engages at ``V >= AUTO_MIN_VOCAB`` (the LM-head regime where the
logits memory dominates); small classifier heads stay on the full-logits
oracle. Heads are found on ``Sequential`` (last layer), ``Model`` (single
Dense output node), or any layer exposing ``fused_head() -> (dense,
param_path)`` (``tfpark``'s ``_BertClassifierNet`` does). The full-logits
objective remains the oracle: ``evaluate``/``predict`` and every
non-matching model keep it.

On a mesh with a ``model`` axis whose size divides the head width — the
predicate under which ``mesh.param_shardings`` actually shards the head
kernel ``P(None, model)`` — the resolved spec additionally routes the
loss through the VOCAB-SHARDED fused CE
(``ops.fused_cross_entropy.sharded_fused_cross_entropy_rows``): each rank
streams only its ``(chunk, V/n)`` weight slice and ``dW`` stays sharded
end to end, so the model-parallel LM head trains without a full-vocab
tensor ever forming on any chip. The ``zoo_train_fused_ce`` gauge carries
a ``sharded`` label so the scrape shows which form is live.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Tuple

from ....ops.fused_cross_entropy import (AUTO_MIN_VOCAB,
                                         fused_sparse_cross_entropy,
                                         sharded_fused_sparse_cross_entropy,
                                         vocab_shard_count)

log = logging.getLogger("analytics_zoo_tpu.training")


def find_head(model) -> Optional[Tuple[object, Tuple[str, ...]]]:
    """``(head_dense_layer, param_path)`` for the model's logits head, or
    None when no unique container-dispatched Dense head exists."""
    from .engine import Model, Sequential
    from .layers.core import Dense

    hook = getattr(model, "fused_head", None)
    if callable(hook):
        return hook()
    if isinstance(model, Sequential) and model.layers:
        head = model.layers[-1]
        if (isinstance(head, Dense)
                and sum(1 for l in model.layers if l is head) == 1):
            return head, (head.name,)
        return None
    if isinstance(model, Model) and len(model.outputs) == 1:
        node = model.outputs[0].node
        if (node.parents and isinstance(node.layer, Dense)
                and sum(1 for n in model._topo
                        if n.layer is node.layer) == 1):
            return node.layer, (node.name,)
    return None


class FusedHeadSpec:
    """A resolved head: applies the trunk (head intercepted to identity)
    and the fused blockwise loss over the head's own params. ``sharded``
    marks the vocab-sharded (model-parallel) form — resolved once per
    loop from the mesh, so every step builder of a loop compiles the
    same collective structure."""

    def __init__(self, head, param_path: Tuple[str, ...],
                 sharded: bool = False):
        self.head = head
        self.param_path = tuple(param_path)
        self.sharded = bool(sharded)

    def head_params(self, params):
        p = params
        for k in self.param_path:
            p = p[k]
        return p

    def apply_and_loss(self, model, params, net_state, x, y, *, rng=None):
        """(loss, new_state) with the head fused into the loss."""
        import jax.numpy as jnp

        from .engine import intercept_layer_calls
        head = self.head

        def hook(layer, p, s, xx, training, lrng):
            if layer is head:
                return xx, s        # identity: expose the hidden states
            return None

        with intercept_layer_calls(hook):
            h, ns = model.apply(params, net_state, x, training=True, rng=rng)
        hp = self.head_params(params)
        w = hp["W"]
        # the objectives oracle indexes numpy-style: a label in [-V, -1]
        # WRAPS (take_along_axis picks logits[V+label]) and still counts
        # in the mean over all rows; anything outside [-V, V) hits the
        # gather's fill mode and NaNs the loss. Replicate both exactly —
        # this silent substitution must be a memory-layout change, never
        # a numerics change (loss-gate comparability across the flag):
        # wrap the in-range negatives, and route doubly-invalid labels
        # to the op's over-range NaN poisoning. Ignore-label masking is
        # the op-level fused_sparse_cross_entropy API, opted into by
        # calling it directly with label<0 rows intact.
        v = w.shape[1]
        labels = jnp.asarray(y).reshape(-1).astype(jnp.int32)
        labels = jnp.where(labels < -v, v,
                           jnp.where(labels < 0, labels + v, labels))
        if self.sharded:
            loss = sharded_fused_sparse_cross_entropy(labels, h, w,
                                                      hp.get("b"))
        else:
            loss = fused_sparse_cross_entropy(labels, h, w, hp.get("b"))
        return loss, ns


def _mode() -> str:
    from ....common.context import tri_state_conf
    flag = tri_state_conf("zoo.train.fused_ce")
    if flag == "auto":
        return "auto"
    return "on" if flag else "off"


def resolve_fused_loss(model, loss_fn: Callable) -> Optional[FusedHeadSpec]:
    """The spec for (model, loss) when the fused path applies, else None."""
    import jax

    from . import objectives

    mode = _mode()
    if mode == "off":
        return None
    found = find_head(model)
    if found is None:
        return None
    head, path = found
    if loss_fn is objectives.sparse_categorical_crossentropy_from_logits:
        # activation="linear" resolves to the registry's identity lambda —
        # the same raw-logits head as activation=None
        from .layers.core import ACTIVATIONS
        if head.activation is not None \
                and head.activation is not ACTIVATIONS["linear"]:
            return None            # activated output: not raw logits
    elif loss_fn is objectives.sparse_categorical_crossentropy:
        if head.activation is not jax.nn.softmax:
            return None            # only softmax probabilities invert to CE
        # the probability-form objective eps-clips before the log, so in
        # saturated regimes its losses/grads genuinely differ from the
        # exact logits CE the fused path computes — a better objective,
        # but NOT the numerics-preserving substitution auto promises.
        # Opting in takes the explicit zoo.train.fused_ce=true.
        if mode != "on":
            return None
    else:
        return None
    if mode == "auto" and head.output_dim < AUTO_MIN_VOCAB:
        return None
    return FusedHeadSpec(head, path, sharded=_head_sharded(head))


def _head_sharded(head) -> bool:
    """Whether the resolved head's kernel is model-sharded under the
    current mesh — the same divisibility predicate
    ``mesh.param_shardings`` applies before committing the Dense
    ``P(None, model)`` spec (an indivisible head falls back to the
    replicated kernel AND the unsharded fused loss together, so the loss
    collectives always match the param layout)."""
    try:
        n_model = vocab_shard_count()
    except Exception:  # zoolint: disable=ZL007 no mesh constructible
        return False
    return n_model > 1 and head.output_dim % n_model == 0
