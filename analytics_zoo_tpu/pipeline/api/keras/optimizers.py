"""Optimizers — parity with ``pipeline/api/keras/optimizers/`` (Adam with LR
schedules, ``AdamWeightDecay.scala`` BERT-style) and the BigDL optim methods
the reference exposes (SGD, Adagrad, RMSprop, Adadelta, Adamax).

Built on optax (gradient transformations compose into the jitted train step),
plus support for the reference's *per-submodule optimizer* feature
(``Estimator(model, optimMethods: Map[String, OptimMethod])``,
``pipeline/estimator/Estimator.scala:65-68``; param-split logic
``Topology.scala:1122-1143``) via ``multi_optimizer``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import jax
import optax

# ---------------------------------------------------------------------------
# LR schedules (the reference's Adam carries schedule variants:
# ``optimizers/Adam.scala`` Default/Plateau/Poly/...)
# ---------------------------------------------------------------------------

def poly_schedule(lr: float, max_iterations: int, power: float = 0.5):
    return optax.polynomial_schedule(
        init_value=lr, end_value=0.0, power=power,
        transition_steps=max_iterations)


def make_schedule(lr: Union[float, Callable], schedule: Optional[str] = None,
                  decay: float = 0.0, **kw) -> Union[float, Callable]:
    if callable(lr):
        return lr
    if schedule == "poly":
        return poly_schedule(lr, kw.get("max_iterations", 10000), kw.get("power", 0.5))
    if schedule == "warmup_linear":
        warm = kw.get("warmup_steps", 0)
        total = kw.get("total_steps", 10000)
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, lr, warm),
             optax.schedules.linear_schedule(lr, 0.0, max(total - warm, 1))],
            [warm])
    if decay > 0:
        return lambda step: lr / (1.0 + decay * step)
    return lr


# ---------------------------------------------------------------------------
# Optimizer constructors (Keras-1 argument conventions)
# ---------------------------------------------------------------------------

def sgd(lr: float = 0.01, momentum: float = 0.0, decay: float = 0.0,
        nesterov: bool = False, **kw) -> optax.GradientTransformation:
    return optax.sgd(make_schedule(lr, decay=decay, **kw),
                     momentum=momentum or None, nesterov=nesterov)


def adam(lr: float = 0.001, beta_1: float = 0.9, beta_2: float = 0.999,
         epsilon: float = 1e-8, decay: float = 0.0, schedule: Optional[str] = None,
         **kw) -> optax.GradientTransformation:
    """``optimizers/Adam.scala`` parity."""
    return optax.adam(make_schedule(lr, schedule=schedule, decay=decay, **kw),
                      b1=beta_1, b2=beta_2, eps=epsilon)


def adam_weight_decay(lr: float = 1e-4, warmup_portion: float = -1.0,
                      total: int = -1, schedule: str = "linear",
                      beta_1: float = 0.9, beta_2: float = 0.999,
                      epsilon: float = 1e-6, weight_decay: float = 0.01,
                      ) -> optax.GradientTransformation:
    """BERT AdamW — ``optimizers/AdamWeightDecay.scala``: linear warmup over
    ``warmup_portion * total`` steps then linear decay to 0."""
    sched = _warmup_linear_decay(lr, warmup_portion, total)
    return optax.adamw(sched, b1=beta_1, b2=beta_2, eps=epsilon,
                       weight_decay=weight_decay)


def _warmup_linear_decay(lr: float, warmup_portion: float, total: int):
    if total > 0 and warmup_portion >= 0:
        warm = max(int(total * warmup_portion), 1)
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, lr, warm),
             optax.schedules.linear_schedule(lr, 0.0, max(total - warm, 1))],
            [warm])
    return lr


def rmsprop(lr: float = 0.001, rho: float = 0.9, epsilon: float = 1e-8, **kw):
    return optax.rmsprop(lr, decay=rho, eps=epsilon)


def adagrad(lr: float = 0.01, **kw):
    return optax.adagrad(lr)


def adadelta(lr: float = 1.0, rho: float = 0.95, epsilon: float = 1e-8, **kw):
    return optax.adadelta(lr, rho=rho, eps=epsilon)


def adamax(lr: float = 0.002, beta_1: float = 0.9, beta_2: float = 0.999,
           epsilon: float = 1e-8, **kw):
    return optax.adamax(lr, b1=beta_1, b2=beta_2, eps=epsilon)


# Each constructor carries its own lr_resolver — the function that reports
# the EFFECTIVE lr (constant or step->lr schedule) the ctor would build from
# the same kwargs. Co-located so a signature/schedule change can't silently
# desynchronize the TensorBoard LearningRate curve from the real training lr.

def _signature_lr(fn, kwargs):
    import inspect
    p = inspect.signature(fn).parameters.get("lr")
    return kwargs.get("lr", p.default if p is not None else None)


def _schedule_resolver(fn):
    def resolve(**kw):
        extra = {k: v for k, v in kw.items()
                 if k not in ("lr", "schedule", "decay")}
        return make_schedule(_signature_lr(fn, kw),
                             schedule=kw.get("schedule"),
                             decay=kw.get("decay", 0.0), **extra)
    return resolve


def _constant_resolver(fn):
    return lambda **kw: _signature_lr(fn, kw)


sgd.lr_resolver = _schedule_resolver(sgd)
adam.lr_resolver = _schedule_resolver(adam)
adam_weight_decay.lr_resolver = lambda **kw: _warmup_linear_decay(
    _signature_lr(adam_weight_decay, kw),
    kw.get("warmup_portion", -1.0), kw.get("total", -1))
rmsprop.lr_resolver = _constant_resolver(rmsprop)
adagrad.lr_resolver = _constant_resolver(adagrad)
adadelta.lr_resolver = _constant_resolver(adadelta)
adamax.lr_resolver = _constant_resolver(adamax)

OPTIMIZERS: Dict[str, Callable[..., optax.GradientTransformation]] = {
    "sgd": sgd,
    "adam": adam,
    "adamw": adam_weight_decay,
    "adam_weight_decay": adam_weight_decay,
    "rmsprop": rmsprop,
    "adagrad": adagrad,
    "adadelta": adadelta,
    "adamax": adamax,
}


def get_optimizer(opt: Union[str, optax.GradientTransformation],
                  **kwargs) -> optax.GradientTransformation:
    if isinstance(opt, optax.GradientTransformation):
        return opt
    if isinstance(opt, str):
        if opt not in OPTIMIZERS:
            raise ValueError(f"unknown optimizer {opt!r}")
        return OPTIMIZERS[opt](**kwargs)
    raise TypeError(f"bad optimizer spec: {opt!r}")


def resolve_lr(opt: Union[str, optax.GradientTransformation], **kwargs):
    """The EFFECTIVE learning rate of a ``compile()`` spec — a float or a
    ``step -> lr`` schedule, via the ``lr_resolver`` registered next to each
    constructor. Feeds the TensorBoard ``LearningRate`` scalar; None for
    pre-built optax objects (their inner schedule isn't introspectable)."""
    if not isinstance(opt, str) or opt not in OPTIMIZERS:
        return None
    ctor = OPTIMIZERS[opt]
    resolver = getattr(ctor, "lr_resolver", None) or _constant_resolver(ctor)
    return resolver(**kwargs)


# ---------------------------------------------------------------------------
# Per-submodule optimizers (Estimator.scala:65-68 / Topology.scala:1122-1143)
# ---------------------------------------------------------------------------

def multi_optimizer(rules: Dict[str, Union[str, optax.GradientTransformation]],
                    default: Union[str, optax.GradientTransformation] = "adam",
                    ) -> optax.GradientTransformation:
    """Route parameter subtrees to different optimizers by top-level name
    prefix. ``rules`` maps a layer-name prefix (the reference splits by
    submodule name, ``Topology.scala:1122-1143``) to an optimizer."""
    keys = list(rules.keys())

    def label_fn(params):
        def label_for(path_prefix):
            for k in keys:
                if path_prefix.startswith(k):
                    return k
            return "__default__"
        return {name: jax.tree.map(lambda _: label_for(name), sub)
                for name, sub in params.items()}

    transforms = {k: get_optimizer(v) for k, v in rules.items()}
    # an explicit "__default__" rule wins over the default parameter
    transforms.setdefault("__default__", get_optimizer(default))
    return optax.multi_transform(transforms, label_fn)


# ---------------------------------------------------------------------------
# Gradient clipping (KerasNet.setGradientClippingByL2Norm / ConstantClipping,
# ``Topology.scala:63-600`` region)
# ---------------------------------------------------------------------------

def with_clipping(opt: optax.GradientTransformation,
                  clip_norm: Optional[float] = None,
                  clip_value: Optional[float] = None,
                  ) -> optax.GradientTransformation:
    chain = []
    if clip_value is not None:
        chain.append(optax.clip(clip_value))
    if clip_norm is not None:
        chain.append(optax.clip_by_global_norm(clip_norm))
    chain.append(opt)
    return optax.chain(*chain) if len(chain) > 1 else opt
