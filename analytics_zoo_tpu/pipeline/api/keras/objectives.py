"""Loss functions — parity with the reference's 15 objectives
(``pipeline/api/keras/objectives/*.scala``: BinaryCrossEntropy,
CategoricalCrossEntropy, SparseCategoricalCrossEntropy, MeanSquaredError,
MeanAbsoluteError, MeanAbsolutePercentageError, MeanSquaredLogarithmicError,
Hinge, SquaredHinge, RankHinge, KullbackLeiblerDivergence, Poisson,
CosineProximity).

Every loss has two forms:

* ``fn(y_true, y_pred) -> scalar`` — mean over the batch (the training path);
* a *per-example* form ``(y_true, y_pred) -> (B,)`` in ``PER_EXAMPLE_LOSSES``
  used by ``evaluate`` to mask padded tail rows out of the statistics.

All computed in float32 for numerical stability regardless of compute dtype.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _f32(y_true, y_pred):
    return jnp.asarray(y_true, jnp.float32), jnp.asarray(y_pred, jnp.float32)


def _per_example(x):
    """Mean over all non-batch axes → shape (B,)."""
    x = jnp.asarray(x)
    if x.ndim <= 1:
        return x.reshape(-1)
    return jnp.mean(x.reshape(x.shape[0], -1), axis=-1)


# ---------------------------------------------------------------------------
# per-example forms
# ---------------------------------------------------------------------------

def mean_squared_error_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    return _per_example(jnp.square(y_pred - y_true))


def mean_absolute_error_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    return _per_example(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    diff = jnp.abs((y_true - y_pred) / jnp.maximum(jnp.abs(y_true), _EPS))
    return 100.0 * _per_example(diff)


def mean_squared_logarithmic_error_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    a = jnp.log(jnp.maximum(y_pred, _EPS) + 1.0)
    b = jnp.log(jnp.maximum(y_true, _EPS) + 1.0)
    return _per_example(jnp.square(a - b))


def binary_crossentropy_pe(y_true, y_pred):
    """Probability-space BCE (the model emits sigmoid outputs, as the
    reference's ``BinaryCrossEntropy`` expects)."""
    y_true, y_pred = _f32(y_true, y_pred)
    p = jnp.clip(y_pred, _EPS, 1.0 - _EPS)
    return _per_example(-(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log1p(-p)))


def binary_crossentropy_from_logits_pe(y_true, y_pred):
    """Fused logits BCE — numerically superior; preferred TPU path."""
    y_true, y_pred = _f32(y_true, y_pred)
    return _per_example(jnp.maximum(y_pred, 0) - y_pred * y_true
                        + jnp.log1p(jnp.exp(-jnp.abs(y_pred))))


def categorical_crossentropy_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    p = jnp.clip(y_pred, _EPS, 1.0)
    return _per_example(-jnp.sum(y_true * jnp.log(p), axis=-1))


def categorical_crossentropy_from_logits_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    logp = jax.nn.log_softmax(y_pred, axis=-1)
    return _per_example(-jnp.sum(y_true * logp, axis=-1))


def sparse_categorical_crossentropy_pe(y_true, y_pred):
    """``SparseCategoricalCrossEntropy.scala`` — integer labels (0-based here;
    the reference uses zeroBasedLabel=true by default too)."""
    y_pred = jnp.asarray(y_pred, jnp.float32)
    labels = jnp.asarray(y_true, jnp.int32).reshape(y_pred.shape[:-1])
    p = jnp.clip(y_pred, _EPS, 1.0)
    picked = jnp.take_along_axis(jnp.log(p), labels[..., None], axis=-1)[..., 0]
    return _per_example(-picked)


def sparse_categorical_crossentropy_from_logits_pe(y_true, y_pred):
    y_pred = jnp.asarray(y_pred, jnp.float32)
    labels = jnp.asarray(y_true, jnp.int32).reshape(y_pred.shape[:-1])
    # the full-logits ORACLE the fused blockwise loss is equivalence-
    # tested against; big-vocab training heads never reach it — the
    # loss resolution reroutes them to ops.fused_cross_entropy
    # (zoo.train.fused_ce, keras/fused_loss.py)
    logp = jax.nn.log_softmax(y_pred, axis=-1)  # zoolint: disable=ZL012 the fused-CE equivalence oracle
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return _per_example(-picked)


def hinge_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    return _per_example(jnp.maximum(1.0 - y_true * y_pred, 0.0))


def squared_hinge_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    return _per_example(jnp.square(jnp.maximum(1.0 - y_true * y_pred, 0.0)))


def kullback_leibler_divergence_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    p = jnp.clip(y_true, _EPS, 1.0)
    q = jnp.clip(y_pred, _EPS, 1.0)
    return _per_example(jnp.sum(p * jnp.log(p / q), axis=-1))


def poisson_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    return _per_example(y_pred - y_true * jnp.log(y_pred + _EPS))


def cosine_proximity_pe(y_true, y_pred):
    y_true, y_pred = _f32(y_true, y_pred)
    t = y_true / (jnp.linalg.norm(y_true, axis=-1, keepdims=True) + _EPS)
    p = y_pred / (jnp.linalg.norm(y_pred, axis=-1, keepdims=True) + _EPS)
    return _per_example(-jnp.sum(t * p, axis=-1))


# ---------------------------------------------------------------------------
# scalar (batch-mean) forms — the training-path API
# ---------------------------------------------------------------------------

def _scalarize(pe_fn):
    def fn(y_true, y_pred):
        return jnp.mean(pe_fn(y_true, y_pred))
    fn.__name__ = pe_fn.__name__[:-3]
    fn.per_example = pe_fn
    return fn


mean_squared_error = _scalarize(mean_squared_error_pe)
mean_absolute_error = _scalarize(mean_absolute_error_pe)
mean_absolute_percentage_error = _scalarize(mean_absolute_percentage_error_pe)
mean_squared_logarithmic_error = _scalarize(mean_squared_logarithmic_error_pe)
binary_crossentropy = _scalarize(binary_crossentropy_pe)
binary_crossentropy_from_logits = _scalarize(binary_crossentropy_from_logits_pe)
categorical_crossentropy = _scalarize(categorical_crossentropy_pe)
categorical_crossentropy_from_logits = _scalarize(categorical_crossentropy_from_logits_pe)
sparse_categorical_crossentropy = _scalarize(sparse_categorical_crossentropy_pe)
sparse_categorical_crossentropy_from_logits = _scalarize(
    sparse_categorical_crossentropy_from_logits_pe)
hinge = _scalarize(hinge_pe)
squared_hinge = _scalarize(squared_hinge_pe)
kullback_leibler_divergence = _scalarize(kullback_leibler_divergence_pe)
poisson = _scalarize(poisson_pe)
cosine_proximity = _scalarize(cosine_proximity_pe)


def rank_hinge(y_true, y_pred, margin: float = 1.0):
    """``RankHinge.scala`` — pairwise ranking loss for QA ranking. Assumes
    consecutive (positive, negative) pairs in the batch, as the reference's
    text-matching pipeline arranges (``feature/common/Relations.scala``).
    Cross-batch structure means there is no per-example form."""
    y_pred = jnp.asarray(y_pred, jnp.float32).reshape(-1)
    pos = y_pred[0::2]
    neg = y_pred[1::2]
    return jnp.mean(jnp.maximum(margin - pos + neg, 0.0))


LOSSES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "bce": binary_crossentropy,
    "bce_with_logits": binary_crossentropy_from_logits,
    "categorical_crossentropy": categorical_crossentropy,
    "cce": categorical_crossentropy,
    "cce_with_logits": categorical_crossentropy_from_logits,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "scce": sparse_categorical_crossentropy,
    "scce_with_logits": sparse_categorical_crossentropy_from_logits,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "rank_hinge": rank_hinge,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
}


def get_loss(loss: Union[str, Callable]) -> Callable:
    if callable(loss):
        return loss
    if loss not in LOSSES:
        raise ValueError(f"unknown loss {loss!r}; available: {sorted(LOSSES)}")
    return LOSSES[loss]


def per_example_loss(loss: Union[str, Callable]) -> Optional[Callable]:
    """Per-example form of a loss, or None if the loss has cross-batch
    structure (rank_hinge) or is a custom callable without one."""
    fn = get_loss(loss)
    return getattr(fn, "per_example", None)
