"""Sharded-embedding resolution — wires ``ops/sharded_embedding`` into
the training loop without touching model code.

``resolve_sharded_embeddings(model)`` recognizes plain ``Embedding``
layers at step-build time (the ``fused_loss.resolve_fused_loss``
pattern) and returns an ``engine.intercept_layer_calls`` hook that
routes their container dispatch through the row-partitioned dedup'd
lookup: the ``(V, D)`` table shards ``P(model, None)``, each distinct id
crosses the interconnect once, and the backward is the sparse
scatter-add VJP. NeuralCF / WideAndDeep / SessionRecommender opt in
purely through configuration — their model code keeps calling the plain
layer.

Mode (``zoo.embed.sharded``: auto | true | false):

* ``auto`` engages on a mesh with ``model > 1`` for tables whose row
  count divides the axis size — the predicate under which
  ``mesh.param_shardings`` can actually commit the ``P(model, None)``
  row spec the intercepted lookup assumes;
* explicit ``true`` engages every plain ``Embedding`` whenever
  ``model > 1`` — an indivisible table is padded inside the lookup and
  its param leaf rides ``param_shardings``'s coalesced
  replicated-fallback warning, so the degradation is visible;
* ``false`` disengages (the layer's own ``jnp.take`` path).

Resolution happens ONCE per loop (``training._loss_application``), so
every step builder compiles the same collective structure; engaged
layers get ``_row_shard`` flipped BEFORE ``param_shardings`` reads the
spec tree (step build precedes sharding resolution in ``fit``). The
``zoo_embed_sharded_tables`` gauge reports how many tables are live on
the sharded engine.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

log = logging.getLogger("analytics_zoo_tpu.training")


def find_embeddings(model) -> List[object]:
    """The container-dispatched plain ``Embedding`` layers of ``model``
    (exactly ``Embedding`` — ``ShardedEmbedding`` already routes through
    the engine itself; ``SparseEmbedding``/``WordEmbedding`` don't
    gather trainable rows by id)."""
    from .engine import Model, Sequential
    from .layers.embeddings import Embedding

    if isinstance(model, Sequential):
        layers = list(model.layers)
    elif isinstance(model, Model):
        layers = [n.layer for n in model._topo]
    else:
        # ZooModel facade (NeuralCF, WideAndDeep, ...): the layers live
        # in the wrapped graph — the ``fused_head()`` see-through idiom
        inner = getattr(model, "model", None)
        if inner is not None and inner is not model:
            return find_embeddings(inner)
        layers = []
    out, seen = [], set()
    for layer in layers:
        if type(layer) is Embedding and id(layer) not in seen:
            seen.add(id(layer))
            out.append(layer)
    return out


def _mode() -> str:
    from ....common.context import tri_state_conf
    flag = tri_state_conf("zoo.embed.sharded")
    if flag == "auto":
        return "auto"
    return "on" if flag else "off"


def resolve_sharded_embeddings(model) -> Optional[Callable]:
    """The layer-dispatch intercept hook for ``model``'s embeddings when
    the sharded engine applies, else None. Flips ``_row_shard`` on every
    engaged layer whose row count divides the ``model`` axis so
    ``param_shardings`` commits the row partitioning the lookup's
    shard_map in_specs declare."""
    from ....ops.sharded_embedding import (model_row_shard_count,
                                           sharded_embedding_lookup)

    mode = _mode()
    if mode == "off":
        return None
    candidates = find_embeddings(model)
    if not candidates:
        return None
    try:
        n_model = model_row_shard_count()
    except Exception:  # zoolint: disable=ZL007 no mesh constructible
        n_model = 1
    if n_model <= 1:
        return None
    if mode == "auto":
        engaged = [l for l in candidates if l.input_dim % n_model == 0]
    else:
        engaged = list(candidates)
    if not engaged:
        return None
    for layer in engaged:
        layer._row_shard = layer.input_dim % n_model == 0
    indivisible = sum(1 for l in engaged if not l._row_shard)
    log.info(
        "sharded embedding engine engaged for %d table(s) over model=%d"
        "%s", len(engaged), n_model,
        f" ({indivisible} padded, param leaf replicated)"
        if indivisible else "")
    _record_engaged(len(engaged))
    engaged_ids = frozenset(id(l) for l in engaged)

    def hook(layer, params, state, x, training, rng):
        if id(layer) not in engaged_ids:
            return None
        import jax.numpy as jnp
        out = sharded_embedding_lookup(params["embeddings"],
                                       x.astype(jnp.int32))
        return out, state

    return hook


def _record_engaged(n: int) -> None:
    from ....observability import default_registry
    default_registry().gauge(
        "zoo_embed_sharded_tables",
        "embedding tables routed through the row-partitioned sharded "
        "lookup in the live training loop").set(n)
