"""Metrics — parity with ``pipeline/api/keras/metrics/`` (Accuracy, AUC,
MAE) plus the validation methods the reference pulls from BigDL (Top1/Top5
accuracy, Loss).

A metric is a pair of jittable functions so evaluation streams over batches
without host sync:

* ``update(y_true, y_pred) -> stats``  — per-batch sufficient statistics
* ``finalize(stats) -> scalar``        — combine (stats are summed over batches)
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Union

import jax.numpy as jnp


class Metric(NamedTuple):
    name: str
    update: Callable  # (y_true, y_pred) -> stats pytree (summable)
    finalize: Callable  # stats -> scalar


def _binary_or_top1(y_true, y_pred):
    y_pred = jnp.asarray(y_pred)
    y_true = jnp.asarray(y_true)
    if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
        pred = jnp.argmax(y_pred, axis=-1)
        true = (jnp.argmax(y_true, axis=-1)
                if y_true.ndim == y_pred.ndim else y_true.reshape(pred.shape))
        correct = (pred == true.astype(pred.dtype))
    else:
        pred = (y_pred.reshape(-1) > 0.5)
        correct = (pred == (y_true.reshape(-1) > 0.5))
    return {"correct": jnp.sum(correct.astype(jnp.float32)),
            "count": jnp.asarray(correct.size, jnp.float32)}


def accuracy() -> Metric:
    """Top-1 / binary accuracy (``metrics/Accuracy.scala``)."""
    return Metric("accuracy", _binary_or_top1,
                  lambda s: s["correct"] / jnp.maximum(s["count"], 1.0))


def top5_accuracy() -> Metric:
    def update(y_true, y_pred):
        true = (jnp.argmax(y_true, axis=-1) if y_true.ndim == y_pred.ndim
                else y_true.reshape(y_pred.shape[:-1])).astype(jnp.int32)
        top5 = jnp.argsort(y_pred, axis=-1)[..., -5:]
        correct = jnp.any(top5 == true[..., None], axis=-1)
        return {"correct": jnp.sum(correct.astype(jnp.float32)),
                "count": jnp.asarray(correct.size, jnp.float32)}
    return Metric("top5_accuracy", update,
                  lambda s: s["correct"] / jnp.maximum(s["count"], 1.0))


def mae() -> Metric:
    def update(y_true, y_pred):
        err = jnp.abs(jnp.asarray(y_pred, jnp.float32)
                      - jnp.asarray(y_true, jnp.float32).reshape(jnp.asarray(y_pred).shape))
        return {"sum": jnp.sum(err), "count": jnp.asarray(err.size, jnp.float32)}
    return Metric("mae", update, lambda s: s["sum"] / jnp.maximum(s["count"], 1.0))


def mse() -> Metric:
    def update(y_true, y_pred):
        err = jnp.square(jnp.asarray(y_pred, jnp.float32)
                         - jnp.asarray(y_true, jnp.float32).reshape(jnp.asarray(y_pred).shape))
        return {"sum": jnp.sum(err), "count": jnp.asarray(err.size, jnp.float32)}
    return Metric("mse", update, lambda s: s["sum"] / jnp.maximum(s["count"], 1.0))


def auc(n_thresholds: int = 200) -> Metric:
    """Streaming AUC via fixed thresholds (``metrics/AUC.scala``).
    Static-shape histogram accumulation — no sort, XLA-friendly."""

    def update(y_true, y_pred):
        scores = jnp.asarray(y_pred, jnp.float32).reshape(-1)
        labels = jnp.asarray(y_true, jnp.float32).reshape(-1)
        thresholds = jnp.linspace(0.0, 1.0, n_thresholds)
        pred_pos = scores[None, :] >= thresholds[:, None]  # (T, N)
        tp = jnp.sum(pred_pos * labels[None, :], axis=1)
        fp = jnp.sum(pred_pos * (1.0 - labels[None, :]), axis=1)
        return {"tp": tp, "fp": fp,
                "pos": jnp.sum(labels), "neg": jnp.sum(1.0 - labels)}

    def finalize(s):
        tpr = s["tp"] / jnp.maximum(s["pos"], 1.0)
        fpr = s["fp"] / jnp.maximum(s["neg"], 1.0)
        # thresholds ascending → fpr descending; integrate |d fpr| * avg tpr
        return jnp.sum((fpr[:-1] - fpr[1:]) * 0.5 * (tpr[:-1] + tpr[1:]))

    return Metric("auc", update, finalize)


METRICS = {
    "accuracy": accuracy,
    "acc": accuracy,
    "top5": top5_accuracy,
    "top5_accuracy": top5_accuracy,
    "mae": mae,
    "mse": mse,
    "auc": auc,
}


def get_metric(m: Union[str, Metric]) -> Metric:
    if isinstance(m, Metric):
        return m
    if m not in METRICS:
        raise ValueError(f"unknown metric {m!r}; available: {sorted(METRICS)}")
    return METRICS[m]()
