"""Metrics — parity with ``pipeline/api/keras/metrics/`` (Accuracy, AUC,
MAE) plus the validation methods the reference pulls from BigDL (Top1/Top5
accuracy, Loss).

A metric is a pair of jittable functions so evaluation streams over batches
without host sync:

* ``update(y_true, y_pred, mask=None) -> stats`` — per-batch sufficient
  statistics. ``mask`` is an optional (B,) 0/1 weight used by ``evaluate`` to
  exclude padded tail rows (the reference pads the last minibatch; here the
  padding is masked out of the statistics instead of miscounted).
* ``finalize(stats) -> scalar`` — combine (stats are summed over batches).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Union

import jax.numpy as jnp


class Metric(NamedTuple):
    name: str
    update: Callable  # (y_true, y_pred, mask=None) -> stats pytree (summable)
    finalize: Callable  # stats -> scalar


def _mask_of(mask, batch):
    if mask is None:
        return jnp.ones((batch,), jnp.float32)
    return jnp.asarray(mask, jnp.float32).reshape(-1)


def _example_weights(mask, shape):
    """Broadcast a per-example (B,) mask over an array of ``shape`` whose
    leading axis is the batch — every element of example i gets weight
    mask[i]. The single place the weighting rule lives."""
    w = _mask_of(mask, shape[0])
    w = w.reshape((shape[0],) + (1,) * (len(shape) - 1))
    return jnp.broadcast_to(w, shape)


def _binary_or_top1(y_true, y_pred, mask=None):
    y_pred = jnp.asarray(y_pred)
    y_true = jnp.asarray(y_true)
    if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
        pred = jnp.argmax(y_pred, axis=-1)
        true = (jnp.argmax(y_true, axis=-1)
                if y_true.ndim == y_pred.ndim else y_true.reshape(pred.shape))
        correct = (pred == true.astype(pred.dtype))
    else:
        # keep the batch axis leading (no flatten) so masking stays per-example
        pred = (y_pred > 0.5)
        correct = (pred == (y_true.reshape(y_pred.shape) > 0.5))
    w = _example_weights(mask, correct.shape)
    return {"correct": jnp.sum(correct.astype(jnp.float32) * w),
            "count": jnp.sum(w)}


def accuracy() -> Metric:
    """Top-1 / binary accuracy (``metrics/Accuracy.scala``)."""
    return Metric("accuracy", _binary_or_top1,
                  lambda s: s["correct"] / jnp.maximum(s["count"], 1.0))


def top5_accuracy() -> Metric:
    def update(y_true, y_pred, mask=None):
        true = (jnp.argmax(y_true, axis=-1) if y_true.ndim == y_pred.ndim
                else y_true.reshape(y_pred.shape[:-1])).astype(jnp.int32)
        top5 = jnp.argsort(y_pred, axis=-1)[..., -5:]
        correct = jnp.any(top5 == true[..., None], axis=-1)
        w = _example_weights(mask, correct.shape)
        return {"correct": jnp.sum(correct.astype(jnp.float32) * w),
                "count": jnp.sum(w)}
    return Metric("top5_accuracy", update,
                  lambda s: s["correct"] / jnp.maximum(s["count"], 1.0))


def _elementwise_stats(err, mask):
    """Sum/count of an elementwise error array, weighted per example."""
    w = _example_weights(mask, err.shape)
    return {"sum": jnp.sum(err * w), "count": jnp.sum(w)}


def mae() -> Metric:
    def update(y_true, y_pred, mask=None):
        err = jnp.abs(jnp.asarray(y_pred, jnp.float32)
                      - jnp.asarray(y_true, jnp.float32).reshape(jnp.asarray(y_pred).shape))
        return _elementwise_stats(err, mask)
    return Metric("mae", update, lambda s: s["sum"] / jnp.maximum(s["count"], 1.0))


def mse() -> Metric:
    def update(y_true, y_pred, mask=None):
        err = jnp.square(jnp.asarray(y_pred, jnp.float32)
                         - jnp.asarray(y_true, jnp.float32).reshape(jnp.asarray(y_pred).shape))
        return _elementwise_stats(err, mask)
    return Metric("mse", update, lambda s: s["sum"] / jnp.maximum(s["count"], 1.0))


def auc(n_thresholds: int = 200) -> Metric:
    """Streaming AUC via fixed thresholds (``metrics/AUC.scala``).
    Static-shape histogram accumulation — no sort, XLA-friendly."""

    def update(y_true, y_pred, mask=None):
        y_pred = jnp.asarray(y_pred, jnp.float32)
        # weight per element BEFORE flattening so a (B,) mask covers
        # multi-dim outputs like (B, T, 1)
        w = _example_weights(mask, y_pred.shape).reshape(-1)
        scores = y_pred.reshape(-1)
        labels = jnp.asarray(y_true, jnp.float32).reshape(-1)
        thresholds = jnp.linspace(0.0, 1.0, n_thresholds)
        pred_pos = scores[None, :] >= thresholds[:, None]  # (T, N)
        tp = jnp.sum(pred_pos * (labels * w)[None, :], axis=1)
        fp = jnp.sum(pred_pos * ((1.0 - labels) * w)[None, :], axis=1)
        return {"tp": tp, "fp": fp,
                "pos": jnp.sum(labels * w), "neg": jnp.sum((1.0 - labels) * w)}

    def finalize(s):
        tpr = s["tp"] / jnp.maximum(s["pos"], 1.0)
        fpr = s["fp"] / jnp.maximum(s["neg"], 1.0)
        # thresholds ascending → fpr descending; integrate |d fpr| * avg tpr
        return jnp.sum((fpr[:-1] - fpr[1:]) * 0.5 * (tpr[:-1] + tpr[1:]))

    return Metric("auc", update, finalize)


METRICS = {
    "accuracy": accuracy,
    "acc": accuracy,
    "top5": top5_accuracy,
    "top5_accuracy": top5_accuracy,
    "mae": mae,
    "mse": mse,
    "auc": auc,
}


def get_metric(m: Union[str, Metric]) -> Metric:
    if isinstance(m, Metric):
        return m
    if m not in METRICS:
        raise ValueError(f"unknown metric {m!r}; available: {sorted(METRICS)}")
    return METRICS[m]()
