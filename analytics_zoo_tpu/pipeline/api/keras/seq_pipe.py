"""Sequence- and pipeline-parallel training-step integration — wires
``parallel/ring_attention.py`` and ``parallel/pipeline.py`` into the
training loop's step builders WITHOUT touching model code, the same
intercept-layer mechanism the fused LM-head loss uses.

Two conf flags, both resolved ONCE per :class:`TrainingLoop` (like the
fused-loss resolution, so every step builder of a loop compiles the same
collective structure):

* ``zoo.train.seq_attention = off | ring | ulysses`` — ``off`` (default)
  keeps the layer-level self-routing (``zoo.seq.mode`` on a seq mesh);
  ``ring``/``ulysses`` FORCE that routing for every attention layer in
  the step: the mode wins over ``zoo.seq.mode``, a missing ``seq`` mesh
  axis fails fast at step-build time, and an attention call that cannot
  ride the mesh (per-query mask, dropout without an rng, indivisible
  shapes) raises instead of silently degrading to full O(T²) attention
  — asking the TRAINING LOOP for sequence parallelism is an explicit
  contract, not a hint.
* ``zoo.train.pipe_stages = S`` — cut the model's homogeneous block run
  (a Sequential's consecutive same-shape, same-type layers, e.g. a
  ``TransformerBlock`` stack) into ``S`` pipeline stages and run it
  through ``gpipe_apply`` over the ``pipe`` mesh axis: the run's params
  stack into one ``(S, ...)`` tree sharded over ``pipe``, the first run
  layer's container dispatch is intercepted to the GPipe schedule, the
  rest become identities. On a mesh without a ``pipe`` axis the same
  stack runs through ``sequential_apply`` — portable from 1 chip to a
  pipelined slice unchanged. ``zoo.train.pipe_microbatch`` sets the
  GPipe microbatch count (0 = the pipe-axis size).

Inside a pipeline stage the attention layers run with seq routing
DISABLED (a nested shard_map over ``seq`` inside the ``pipe`` shard_map
is not a thing) — pick ONE of sequence or pipeline parallelism per
layer run.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
from typing import List, Optional

log = logging.getLogger("analytics_zoo_tpu.training")

#: trace-time seq-attention override for layers' ``_seq_routing``:
#: None = unset (layer self-routing), "off" = routing disabled (inside
#: pipeline stages), "ring"/"ulysses" = forced mode + strict fallback
_FORCED_SEQ_MODE: contextvars.ContextVar = contextvars.ContextVar(
    "zoo_forced_seq_mode", default=None)


def forced_seq_mode() -> Optional[str]:
    """The training loop's seq-attention override for the current trace
    scope (see module docstring)."""
    return _FORCED_SEQ_MODE.get()


@contextlib.contextmanager
def seq_attention_scope(mode: Optional[str]):
    """Scope the seq-attention override over a step trace; ``None`` is a
    no-op (the layer-level routing stands)."""
    if mode is None:
        yield
        return
    token = _FORCED_SEQ_MODE.set(mode)
    try:
        yield
    finally:
        _FORCED_SEQ_MODE.reset(token)


def resolve_seq_attention() -> Optional[str]:
    """``zoo.train.seq_attention`` → None (off) or the forced mode, with
    the mesh validated at step-build time: forcing sequence parallelism
    without a ``seq`` mesh axis is a configuration error, not a warning
    buried in a training log."""
    from ....common.context import FALSE_FLAG_SPELLINGS, get_zoo_context
    from ....parallel import mesh as mesh_lib

    mode = str(get_zoo_context().get("zoo.train.seq_attention",
                                     "off")).strip().lower()
    if mode in FALSE_FLAG_SPELLINGS or mode in ("none", "off"):
        return None
    if mode not in ("ring", "ulysses"):
        raise ValueError(f"zoo.train.seq_attention must be "
                         f"off|ring|ulysses, got {mode!r}")
    mesh = mesh_lib.global_mesh()
    n_seq = int(mesh.shape[mesh_lib.SEQ_AXIS])
    if n_seq <= 1:
        raise ValueError(
            f"zoo.train.seq_attention={mode} needs a seq mesh axis > 1 "
            f"(current mesh: {dict(mesh.shape)}); set zoo.mesh.seq")
    log.info("sequence-parallel attention forced for this training loop: "
             "%s over seq=%d (zoo.train.seq_attention)", mode, n_seq)
    return mode


class PipeStageSpec:
    """A resolved pipeline cut: the consecutive homogeneous layer run a
    Sequential's step intercepts into one GPipe schedule."""

    def __init__(self, layers: List, mesh, pipe_size: int,
                 stages_per_rank: int, n_micro: int):
        self.layers = list(layers)
        self.mesh = mesh
        self.pipe_size = int(pipe_size)
        self.stages_per_rank = int(stages_per_rank)
        self.n_micro = int(n_micro)

    def hook(self, params, training: bool):
        """The intercept-layer hook: the run's FIRST layer dispatch runs
        the whole stacked-and-sharded pipeline; the remaining run
        members become identities (their compute already happened inside
        the schedule)."""
        import jax
        import jax.numpy as jnp

        from ....parallel import pipeline as pipe_lib

        first = self.layers[0]
        members = {id(l) for l in self.layers}
        ref = first
        spec = self

        def stage_fn(p_stage, h, srng):
            # one homogeneous stage = one run layer's code on the
            # stacked param row; seq routing is disabled inside (no
            # nested shard_map over the seq axis from a pipe stage)
            with seq_attention_scope("off"):
                return ref.call(p_stage, h, training=training, rng=srng)

        def _hook(layer, p, s, x, training_, rng):
            if id(layer) not in members:
                return None
            if layer is not first:
                return x, s         # already computed inside the schedule
            if not hasattr(x, "shape"):
                raise ValueError(
                    "zoo.train.pipe_stages: the pipelined block run must "
                    "take a single array input (multi-input runs — e.g. "
                    "masked BERT blocks — cannot stack)")
            per_layer = [params[l.name] for l in spec.layers]
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
            if spec.pipe_size > 1:
                y = pipe_lib.gpipe_apply(
                    stage_fn, stacked, x, mesh=spec.mesh,
                    n_micro=spec.n_micro, rng=rng,
                    stages_per_rank=spec.stages_per_rank)
            else:
                y = pipe_lib.sequential_apply(stage_fn, stacked, x,
                                              len(spec.layers), rng=rng)
            return y, s

        return _hook


def _config_sig(layer, depth: int = 2):
    """A layer's hyperparameter signature: every public, non-Layer,
    non-name attribute (plus sub-layers' signatures one level down —
    a TransformerBlock's causal/attn_drop live on its attention
    sub-layer). Stage homogeneity must compare CONFIG, not just param
    shapes: ``Dense(V, activation="relu")`` and ``Dense(V,
    activation="tanh")`` stack identically but compute differently, and
    the schedule applies the FIRST layer's code to every stage — a
    config mismatch must break the run, never be silently overwritten."""
    from .engine import Layer

    out = {}
    for k, v in sorted(vars(layer).items()):
        if k.startswith("_") or k == "name":
            continue
        if isinstance(v, Layer):
            out[k] = _config_sig(v, depth - 1) if depth > 0 else type(v)
        elif isinstance(v, (list, tuple)) and any(
                isinstance(e, Layer) for e in v):
            out[k] = tuple(_config_sig(e, depth - 1) if depth > 0
                           else type(e) for e in v)
        elif callable(v):
            # registry activations resolve by NAME (the same "relu"
            # from two Dense ctors may or may not be one object; repr
            # would compare addresses)
            out[k] = getattr(v, "__name__", repr(v))
        else:
            out[k] = repr(v)
    return (type(layer).__name__, tuple(out.items()))


def _stackable_run(model) -> List:
    """The longest run of consecutive Sequential layers with identical
    type, CONFIG and param structure/shapes (the stacked-stage
    precondition) and no net state. Requires built params."""
    import jax

    layers = getattr(model, "layers", None)
    params = getattr(model, "params", None)
    if not layers or params is None:
        return []
    state = getattr(model, "net_state", None) or {}

    def sig(layer):
        p = params.get(layer.name)
        if p is None or layer.name in state:
            return None
        shapes = jax.tree.map(lambda a: tuple(getattr(a, "shape", ())), p)
        return (_config_sig(layer), str(shapes))

    best: List = []
    run: List = []
    prev_sig = None
    for layer in layers:
        s = sig(layer)
        if s is not None and s == prev_sig:
            run.append(layer)
        else:
            run = [layer] if s is not None else []
        prev_sig = s
        if len(run) > len(best):
            best = list(run)
    return best if len(best) >= 2 else []


def resolve_pipe_spec(model) -> Optional[PipeStageSpec]:
    """``zoo.train.pipe_stages`` → the resolved :class:`PipeStageSpec`
    (or None when off). Mis-configuration fails fast at step-build time:
    a pipeline the model cannot be cut into must not silently train
    un-pipelined."""
    from ....common.context import get_zoo_context
    from ....parallel import mesh as mesh_lib
    from .engine import Sequential

    stages = int(get_zoo_context().get("zoo.train.pipe_stages", 0) or 0)
    if stages <= 0:
        return None
    if not isinstance(model, Sequential):
        raise ValueError(
            "zoo.train.pipe_stages needs a Sequential model (the stage "
            "cut stacks a consecutive layer run); got "
            f"{type(model).__name__}")
    run = _stackable_run(model)
    if len(run) != stages:
        raise ValueError(
            f"zoo.train.pipe_stages={stages} but the model's stackable "
            f"block run has {len(run)} layer(s) "
            f"({[l.name for l in run]}) — the stage count must equal "
            f"the homogeneous run length")
    mesh = mesh_lib.global_mesh()
    pipe_size = int(mesh.shape[mesh_lib.PIPE_AXIS])
    if pipe_size > 1 and stages % pipe_size != 0:
        raise ValueError(
            f"zoo.train.pipe_stages={stages} does not divide by the "
            f"pipe mesh axis ({pipe_size})")
    n_micro = int(get_zoo_context().get("zoo.train.pipe_microbatch", 0)
                  or 0)
    if n_micro <= 0:
        n_micro = max(pipe_size, 1)
    log.info("pipeline-parallel block run resolved: %d stage(s) over "
             "pipe=%d, %d microbatch(es) (zoo.train.pipe_stages; %s)",
             stages, pipe_size, n_micro,
             "GPipe schedule" if pipe_size > 1
             else "sequential fallback — no pipe mesh axis")
    return PipeStageSpec(run, mesh, pipe_size,
                         stages_per_rank=max(stages // max(pipe_size, 1),
                                             1),
                         n_micro=n_micro)


@contextlib.contextmanager
def pipe_intercept(spec: Optional[PipeStageSpec], params, training: bool):
    """Scope the pipeline intercept over a step trace; no-op for
    ``spec=None``. Chains under any inner intercept (the fused-loss
    head hook) via ``intercept_layer_calls``'s nesting."""
    if spec is None:
        yield
        return
    from .engine import intercept_layer_calls
    with intercept_layer_calls(spec.hook(params, training)):
        yield
