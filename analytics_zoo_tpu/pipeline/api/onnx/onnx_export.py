"""ONNX export — serialize a trained model to a standard ``.onnx`` file.

The reference's escape hatch is exporting trained definitions to
TF/Keras2 via a spawned python (``Topology.scala:557-572``,
``Net.scala:264+``); the portable interchange format today is ONNX, so
this exporter writes ModelProto with the in-repo wire codec
(``utils/proto.py`` — no onnx package needed), the inverse of
``onnx_loader.py``.

Scope: the common feed-forward subset — Dense (Gemm), Convolution2D /
pooling / BatchNormalization (exported in ONNX's NCHW layout with
Transpose bridges from this framework's NHWC), Flatten/Reshape/Dropout,
activations, softmax. Models touching anything else fail loudly with the
layer name. Round-trip fidelity is tested through ``OnnxLoader`` and the
torch-oracle-checked loader op set.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ....utils.proto import field_bytes, field_varint, varint
from ..keras.engine import KerasNet, Layer, Sequential
from ..keras.layers import (Activation, BatchNormalization, Convolution2D,
                            Dense, Dropout, Flatten, GlobalAveragePooling2D,
                            MaxPooling2D, AveragePooling2D, Reshape)

__all__ = ["export_onnx"]


# ---------------------------------------------------------------------------
# proto writers (onnx.proto3 subset — field numbers per the spec)
# ---------------------------------------------------------------------------

def _tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    code = {np.dtype(np.float32): 1, np.dtype(np.int64): 7}[arr.dtype]
    buf = b"".join(field_varint(1, d) for d in arr.shape)
    buf += field_varint(2, code)
    buf += field_bytes(8, name.encode())
    buf += field_bytes(9, arr.tobytes())
    return buf


def _attr_i(name: str, v: int) -> bytes:
    return (field_bytes(1, name.encode()) + field_varint(3, v)
            + field_varint(20, 2))


def _attr_f(name: str, v: float) -> bytes:
    return (field_bytes(1, name.encode())
            + varint((2 << 3) | 5) + struct.pack("<f", v)
            + field_varint(20, 1))


def _attr_ints(name: str, vs) -> bytes:
    buf = field_bytes(1, name.encode())
    for v in vs:
        buf += field_varint(8, int(v))
    return buf + field_varint(20, 7)


def _node(op: str, inputs, outputs, attrs=()) -> bytes:
    buf = b"".join(field_bytes(1, i.encode()) for i in inputs)
    buf += b"".join(field_bytes(2, o.encode()) for o in outputs)
    buf += field_bytes(4, op.encode())
    buf += b"".join(field_bytes(5, a) for a in attrs)
    return buf


def _value_info(name: str, shape=None) -> bytes:
    """ValueInfoProto WITH TypeProto (onnx.checker requires typed graph
    inputs/outputs): float32 tensor, symbolic "N" for the batch dim."""
    buf = field_bytes(1, name.encode())
    if shape is not None:
        dims = b""
        for d in shape:
            if d is None:
                dims += field_bytes(1, field_bytes(2, b"N"))  # dim_param
            else:
                dims += field_bytes(1, field_varint(1, int(d)))
        tensor_type = field_varint(1, 1) + field_bytes(2, dims)
        buf += field_bytes(2, field_bytes(1, tensor_type))
    return buf


def _model_bytes(nodes, initializers, inputs, outputs) -> bytes:
    g = field_bytes(2, b"analytics_zoo_tpu")  # GraphProto.name (checker-required)
    g += b"".join(field_bytes(1, n) for n in nodes)
    g += b"".join(field_bytes(5, t) for t in initializers)
    g += b"".join(field_bytes(11, _value_info(n, s)) for n, s in inputs)
    g += b"".join(field_bytes(12, _value_info(n, s)) for n, s in outputs)
    # ir_version 8, graph, opset_import {version 13}
    opset = field_varint(2, 13)
    return (field_varint(1, 8) + field_bytes(7, g)
            + field_bytes(8, opset))


# ---------------------------------------------------------------------------
# layer → node emission (data flows in ONNX NCHW between conv-family ops)
# ---------------------------------------------------------------------------

_ONNX_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softmax": "Softmax", "elu": "Elu", "selu": "Selu",
             "softplus": "Softplus", "softsign": "Softsign",
             "linear": None}


class _Emitter:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.inits: List[bytes] = []
        self._uid = 0

    def name(self, base: str) -> str:
        self._uid += 1
        return f"{base}_{self._uid}"

    def init(self, base: str, arr: np.ndarray) -> str:
        n = self.name(base)
        self.inits.append(_tensor(n, np.asarray(arr)))
        return n

    def emit(self, op: str, inputs, attrs=(), base: Optional[str] = None
             ) -> str:
        out = self.name(base or op.lower())
        self.nodes.append(_node(op, inputs, [out], attrs))
        return out

    def activation(self, act_name: Optional[str], cur: str,
                   nchw: bool = False) -> str:
        if act_name is None or act_name == "linear":
            return cur
        if act_name not in _ONNX_ACT or _ONNX_ACT[act_name] is None:
            raise NotImplementedError(
                f"activation {act_name!r} has no ONNX export mapping")
        attrs = ()
        if act_name == "softmax" and nchw:
            # the framework softmaxes the channel axis (last, NHWC); in the
            # exported NCHW layout channels sit at axis 1
            attrs = [_attr_i("axis", 1)]
        return self.emit(_ONNX_ACT[act_name], [cur], attrs)


def _act_name(layer) -> Optional[str]:
    # layers store the callable; the constructor name survives on Dense/etc
    # via the Activation registry lookup — recover it by identity
    from ..keras.layers.core import ACTIVATIONS
    fn = getattr(layer, "activation", None)
    if fn is None:
        return None
    for k, v in ACTIVATIONS.items():
        if v is fn:
            return k
    raise NotImplementedError(
        f"{layer.name}: custom activation can't be exported")


def _export_layer(e: _Emitter, layer: Layer, params: Dict[str, Any],
                  state: Dict[str, Any], cur: str, nchw: bool,
                  in_shape=None) -> Tuple[str, bool]:
    """Returns (output name, data-is-NCHW). Conv-family ops run in NCHW;
    a Transpose bridge is inserted at layout changes."""
    def p(k):
        return np.asarray(params[k], np.float32)

    if isinstance(layer, Dense):
        if nchw:
            raise NotImplementedError(
                f"{layer.name}: Dense after conv needs Flatten/"
                f"GlobalAveragePooling2D first")
        if in_shape is not None and len(in_shape) > 2:
            raise NotImplementedError(
                f"{layer.name}: Dense on rank-{len(in_shape)} input has no "
                f"valid ONNX Gemm export (A must be 2D); Flatten first")
        w = e.init(layer.name + "_W", p("W"))          # (in, out)
        ins = [cur, w]
        attrs = []
        if layer.bias:
            ins.append(e.init(layer.name + "_b", p("b")))
        out = e.emit("Gemm", ins, attrs, base=layer.name)
        return e.activation(_act_name(layer), out), False

    if isinstance(layer, Convolution2D) and type(layer) is Convolution2D:
        if not nchw:
            cur = e.emit("Transpose", [cur],
                         [_attr_ints("perm", [0, 3, 1, 2])])
        w = e.init(layer.name + "_W",
                   p("W").transpose(3, 2, 0, 1))       # HWIO -> OIHW
        ins = [cur, w]
        if layer.bias:
            ins.append(e.init(layer.name + "_b", p("b")))
        kh, kw = p("W").shape[0], p("W").shape[1]
        attrs = [_attr_ints("kernel_shape", [kh, kw]),
                 _attr_ints("strides", list(layer.subsample)),
                 _attr_ints("dilations", list(layer.dilation))]
        if layer.border_mode.lower() == "same":
            attrs.append(field_bytes(1, b"auto_pad")
                         + field_bytes(4, b"SAME_UPPER")
                         + field_varint(20, 3))
        out = e.emit("Conv", ins, attrs, base=layer.name)
        return e.activation(_act_name(layer), out, nchw=True), True

    if isinstance(layer, BatchNormalization):
        rank = len(in_shape) if in_shape is not None else 4
        if rank == 3:
            raise NotImplementedError(
                f"{layer.name}: BatchNormalization on rank-3 (B, T, C) "
                f"input exports to ONNX axis-1 semantics, which differ "
                f"from this framework's last-axis normalization")
        if not nchw and rank == 4:
            cur = e.emit("Transpose", [cur],
                         [_attr_ints("perm", [0, 3, 1, 2])])
            nchw = True
        # rank-2 (B, C): ONNX BatchNormalization takes C at axis 1 as-is
        mean = np.asarray(state["moving_mean"], np.float32)
        var = np.asarray(state["moving_var"], np.float32)
        gamma = (p("gamma") if "gamma" in params
                 else np.ones_like(mean))
        beta = (p("beta") if "beta" in params
                else np.zeros_like(mean))
        ins = [cur,
               e.init(layer.name + "_g", gamma),
               e.init(layer.name + "_b", beta),
               e.init(layer.name + "_m", mean),
               e.init(layer.name + "_v", var)]
        out = e.emit("BatchNormalization", ins,
                     [_attr_f("epsilon", float(layer.epsilon))],
                     base=layer.name)
        return out, nchw

    if isinstance(layer, (MaxPooling2D, AveragePooling2D)):
        if not nchw:
            cur = e.emit("Transpose", [cur],
                         [_attr_ints("perm", [0, 3, 1, 2])])
        op = ("MaxPool" if isinstance(layer, MaxPooling2D)
              else "AveragePool")
        attrs = [_attr_ints("kernel_shape", list(layer.pool_size)),
                 _attr_ints("strides", list(layer.strides))]
        if layer.border_mode.lower() == "same":
            attrs.append(field_bytes(1, b"auto_pad")
                         + field_bytes(4, b"SAME_UPPER")
                         + field_varint(20, 3))
        return e.emit(op, [cur], attrs, base=layer.name), True

    if isinstance(layer, GlobalAveragePooling2D):
        if not nchw:
            cur = e.emit("Transpose", [cur],
                         [_attr_ints("perm", [0, 3, 1, 2])])
        out = e.emit("GlobalAveragePool", [cur], base=layer.name)
        return e.emit("Flatten", [out], [_attr_i("axis", 1)]), False

    if isinstance(layer, Flatten):
        if nchw:  # restore NHWC order before flattening: the in-framework
            # flatten sees NHWC memory order
            cur = e.emit("Transpose", [cur],
                         [_attr_ints("perm", [0, 2, 3, 1])])
        return e.emit("Flatten", [cur], [_attr_i("axis", 1)]), False

    if isinstance(layer, Dropout):
        return cur, nchw  # inference graph: identity

    if isinstance(layer, Activation):
        if layer.activation_name is None:
            raise NotImplementedError(
                f"{layer.name}: callable activation can't be exported")
        return e.activation(layer.activation_name, cur, nchw=nchw), nchw

    if isinstance(layer, Reshape):
        if nchw:  # in-framework Reshape sees NHWC memory order
            cur = e.emit("Transpose", [cur],
                         [_attr_ints("perm", [0, 2, 3, 1])])
        shape = e.init(layer.name + "_shape", np.asarray(
            (-1,) + tuple(layer.target_shape), np.int64))
        return e.emit("Reshape", [cur, shape], base=layer.name), False

    raise NotImplementedError(
        f"layer {layer.name} ({type(layer).__name__}) has no ONNX export "
        f"mapping")


def export_onnx(model: KerasNet, path: str) -> str:
    """Write ``model`` (a built Sequential of exportable layers) to
    ``path`` as ONNX. Conv-family models export with NCHW inputs (the ONNX
    convention); pass images as (B, C, H, W) to the exported graph."""
    if not isinstance(model, Sequential):
        raise NotImplementedError(
            "export_onnx covers Sequential models (graph Models: walk "
            "model.new_graph sub-Sequentials or export per-branch)")
    if model.params is None:
        raise ValueError("model has no weights; fit() or init_weights() "
                         "first")
    e = _Emitter()
    cur = "input"
    shapes = list(getattr(model, "_shapes", [])) or [None] * len(model.layers)
    in_shape = shapes[0] if shapes and shapes[0] is not None else None
    if in_shape is None:
        # an untyped graph input fails onnx.checker — refuse early rather
        # than emit a file the stated compatibility guarantee rejects
        raise ValueError(
            "export_onnx needs the model's input shape: build the first "
            "layer with input_shape=... (or init_weights(input_shape=...)) "
            "before exporting")
    # a stack starting conv-family takes NCHW input per ONNX convention
    nchw = bool(model.layers) and isinstance(
        model.layers[0], (Convolution2D, MaxPooling2D, AveragePooling2D))
    net_state = model.net_state or {}
    for layer, lshape in zip(model.layers, shapes):
        cur, nchw = _export_layer(e, layer, model.params.get(layer.name, {}),
                                  net_state.get(layer.name, {}), cur, nchw,
                                  in_shape=lshape)
    in_decl = None
    if in_shape is not None:
        dims = list(in_shape)
        if len(dims) == 4 and isinstance(
                model.layers[0], (Convolution2D, MaxPooling2D,
                                  AveragePooling2D)):
            dims = [dims[0], dims[3], dims[1], dims[2]]  # NHWC -> NCHW decl
        in_decl = dims
    out_shape = getattr(model, "_built_output_shape", None)
    out_decl = list(out_shape) if isinstance(out_shape, tuple) else None
    if out_decl is not None and len(out_decl) == 4 and nchw:
        out_decl = [out_decl[0], out_decl[3], out_decl[1], out_decl[2]]
    blob = _model_bytes(e.nodes, e.inits, [("input", in_decl)],
                        [(cur, out_decl)])
    if not path.endswith(".onnx"):
        path += ".onnx"
    with open(path, "wb") as f:
        f.write(blob)
    return path
