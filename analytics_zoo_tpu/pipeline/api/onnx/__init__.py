"""ONNX model import (SURVEY §2.2; reference
``pyzoo/zoo/pipeline/api/onnx/onnx_loader.py`` maps ONNX nodes onto BigDL
modules). Dependency-free: the ``.onnx`` protobuf is parsed with the
package's own wire-format codec, and the graph executes as a native Layer —
so an imported ONNX model predicts, fine-tunes, shards, and serializes like
any other model here."""

from .onnx_loader import OnnxLoader, OnnxNet, load_onnx  # noqa: F401
from .onnx_export import export_onnx  # noqa: F401
