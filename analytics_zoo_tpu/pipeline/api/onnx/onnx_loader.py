"""ONNX loader — parse ``.onnx`` (ModelProto) files with the in-repo proto
codec and execute the graph as a native JAX ``Layer``.

Scope mirrors the reference loader's op coverage
(``pyzoo/zoo/pipeline/api/onnx/mapper/*``: Gemm, Conv, BatchNormalization,
pooling, activations, shape ops): the common inference subset. Initializers
become the Layer's params, so imported models are immediately fine-tunable
under the jitted train step. ONNX semantics are executed as-is (NCHW convs
— XLA retiles layouts for TPU on its own).
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ....utils.proto import parse_fields, parse_varint
from ...api.keras.engine import Layer

__all__ = ["OnnxLoader", "OnnxNet", "load_onnx"]

# TensorProto.DataType → numpy
_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 5: np.int16,
           6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
           11: np.float64}


def _as_int(payload: bytes) -> int:
    v, _ = parse_varint(payload, 0)
    return v


def _signed(v: int) -> int:
    """Protobuf int64 varints are two's-complement; fold back to signed."""
    return v - (1 << 64) if v >= (1 << 63) else v


# ---------------------------------------------------------------------------
# proto decoding (onnx.proto3 subset)
# ---------------------------------------------------------------------------

def _decode_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    dtype = np.float32
    name = ""
    raw: Optional[bytes] = None
    floats: List[float] = []
    int64s: List[int] = []
    for num, wt, payload in parse_fields(buf):
        if num == 1:          # dims (packed by proto3 default, or repeated)
            if wt == 2:
                i = 0
                while i < len(payload):
                    v, i = parse_varint(payload, i)
                    dims.append(_signed(v))
            else:
                dims.append(_signed(_as_int(payload)))
        elif num == 2:        # data_type
            dtype = _DTYPES[_as_int(payload)]
        elif num == 8 and wt == 2:   # name
            name = payload.decode("utf-8")
        elif num == 9 and wt == 2:   # raw_data
            raw = payload
        elif num == 4:        # float_data (packed or repeated)
            if wt == 2:
                floats.extend(struct.unpack(f"<{len(payload) // 4}f", payload))
            else:
                floats.append(struct.unpack("<f", payload)[0])
        elif num == 7:        # int64_data
            if wt == 2:
                i = 0
                while i < len(payload):
                    v, i = parse_varint(payload, i)
                    int64s.append(_signed(v))
            else:
                int64s.append(_signed(_as_int(payload)))
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dtype)
    elif floats:
        arr = np.asarray(floats, np.float32)
    elif int64s:
        arr = np.asarray(int64s, np.int64)
    else:
        arr = np.zeros(0, dtype)
    return name, arr.reshape(dims) if dims else arr


def _decode_attribute(buf: bytes) -> Tuple[str, Any]:
    name, value = "", None
    for num, wt, payload in parse_fields(buf):
        if num == 1 and wt == 2:
            name = payload.decode("utf-8")
        elif num == 2:        # f
            value = struct.unpack("<f", payload)[0]
        elif num == 3:        # i
            value = _signed(_as_int(payload))
        elif num == 4 and wt == 2:  # s
            value = payload.decode("utf-8", "replace")
        elif num == 5 and wt == 2:  # t (tensor)
            value = _decode_tensor(payload)[1]
        elif num == 7:        # floats (packed or repeated; chunks accumulate)
            vals = value if isinstance(value, list) else []
            if wt == 2:
                vals.extend(struct.unpack(f"<{len(payload) // 4}f", payload))
            else:
                vals.append(struct.unpack("<f", payload)[0])
            value = vals
        elif num == 8:        # ints (packed or repeated)
            vals = value if isinstance(value, list) else []
            if wt == 2:
                i = 0
                while i < len(payload):
                    v, i = parse_varint(payload, i)
                    vals.append(_signed(v))
            else:
                vals.append(_signed(_as_int(payload)))
            value = vals
    return name, value


def _decode_node(buf: bytes) -> Dict[str, Any]:
    node = {"inputs": [], "outputs": [], "op": "", "name": "", "attrs": {}}
    for num, wt, payload in parse_fields(buf):
        if num == 1 and wt == 2:
            node["inputs"].append(payload.decode("utf-8"))
        elif num == 2 and wt == 2:
            node["outputs"].append(payload.decode("utf-8"))
        elif num == 3 and wt == 2:
            node["name"] = payload.decode("utf-8")
        elif num == 4 and wt == 2:
            node["op"] = payload.decode("utf-8")
        elif num == 5 and wt == 2:
            k, v = _decode_attribute(payload)
            node["attrs"][k] = v
    return node


def _decode_value_info(buf: bytes) -> str:
    for num, wt, payload in parse_fields(buf):
        if num == 1 and wt == 2:
            return payload.decode("utf-8")
    return ""


def _decode_graph(buf: bytes) -> Dict[str, Any]:
    g = {"nodes": [], "initializers": {}, "inputs": [], "outputs": []}
    for num, wt, payload in parse_fields(buf):
        if num == 1 and wt == 2:
            g["nodes"].append(_decode_node(payload))
        elif num == 5 and wt == 2:
            name, arr = _decode_tensor(payload)
            g["initializers"][name] = arr
        elif num == 11 and wt == 2:
            g["inputs"].append(_decode_value_info(payload))
        elif num == 12 and wt == 2:
            g["outputs"].append(_decode_value_info(payload))
    return g


def _decode_opset(buf: bytes) -> Tuple[str, int]:
    domain, version = "", 0
    for num, wt, payload in parse_fields(buf):
        if num == 1 and wt == 2:
            domain = payload.decode("utf-8")
        elif num == 2:
            version = _signed(_as_int(payload))
    return domain, version


def _decode_model(buf: bytes) -> Dict[str, Any]:
    graph, opset = None, 13
    for num, wt, payload in parse_fields(buf):
        if num == 7 and wt == 2:    # ModelProto.graph
            graph = _decode_graph(payload)
        elif num == 8 and wt == 2:  # ModelProto.opset_import
            domain, version = _decode_opset(payload)
            if domain in ("", "ai.onnx") and version:
                opset = version
    if graph is None:
        raise ValueError("no GraphProto found — not an ONNX ModelProto?")
    graph["opset"] = opset
    return graph


# ---------------------------------------------------------------------------
# op execution
# ---------------------------------------------------------------------------

def _conv_padding(attrs, spatial, in_shape=None, kernel=None, strides=None):
    auto = attrs.get("auto_pad")
    if auto == "SAME_UPPER":
        return "SAME"
    if auto == "SAME_LOWER":
        # XLA's "SAME" puts the odd pad at the END (SAME_UPPER); ONNX
        # SAME_LOWER wants it at the START — compute explicit pairs
        pads = []
        for i in range(spatial):
            size, k = int(in_shape[2 + i]), int(kernel[i])
            s = int(strides[i]) if strides else 1
            total = max((-(-size // s) - 1) * s + k - size, 0)
            pads.append((total - total // 2, total // 2))
        return pads
    pads = attrs.get("pads")
    if not pads:
        return [(0, 0)] * spatial
    half = len(pads) // 2
    return list(zip(pads[:half], pads[half:]))


def _pool_cfg(x, attrs):
    """Window/stride/pad config shared by Max/AveragePool. Returns
    ``(window, strides, real_pads, full_pads)`` where full_pads includes the
    ceil-mode END extension and real_pads is the user-requested padding
    (the distinction matters for AveragePool divisors)."""
    k = attrs["kernel_shape"]
    strides = attrs.get("strides", [1] * len(k))
    pads = _conv_padding(attrs, len(k), x.shape, k, strides)
    real = pads
    if attrs.get("ceil_mode") and not isinstance(pads, str):
        full = []
        for i in range(len(k)):
            size = int(x.shape[2 + i]) + pads[i][0] + pads[i][1]
            s, kk = int(strides[i]), int(k[i])
            out_ceil = -(-(size - kk) // s) + 1
            # torch's clip rule (Pool.h): drop a window that would START at
            # or past input + BEGIN padding — end padding doesn't host
            # window starts
            if (out_ceil - 1) * s >= int(x.shape[2 + i]) + pads[i][0]:
                out_ceil -= 1
            need = max(0, (out_ceil - 1) * s + kk - size)
            full.append((pads[i][0], pads[i][1] + need))
        pads = full
    return ((1, 1) + tuple(k), (1, 1) + tuple(strides), real, pads)


def _pool(x, op, init, attrs):
    window, strd, _, pads = _pool_cfg(x, attrs)
    pad_cfg = (pads if isinstance(pads, str)
               else [(0, 0), (0, 0)] + list(pads))
    return jax.lax.reduce_window(x, init, op, window, strd, pad_cfg)


def _run_node(node: Dict[str, Any], vals: Dict[str, Any],
              training: bool, rng=None, opset: int = 13) -> None:
    op = node["op"]
    attrs = node["attrs"]
    ins = [vals[n] if n else None for n in node["inputs"]]
    out = node["outputs"][0]

    if op == "Gemm":
        a, b = ins[0], ins[1]
        if attrs.get("transA"):
            a = a.T
        if attrs.get("transB"):
            b = b.T
        y = attrs.get("alpha", 1.0) * jnp.matmul(
            a, b, preferred_element_type=jnp.float32)
        if len(ins) > 2 and ins[2] is not None:
            y = y + attrs.get("beta", 1.0) * ins[2]
        vals[out] = y
    elif op == "MatMul":
        vals[out] = jnp.matmul(ins[0], ins[1],
                               preferred_element_type=jnp.float32)
    elif op in ("Add", "Sub", "Mul", "Div", "Pow"):
        fn = {"Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
              "Div": jnp.divide, "Pow": jnp.power}[op]
        vals[out] = fn(ins[0], ins[1])
    elif op == "Relu":
        vals[out] = jnp.maximum(ins[0], 0)
    elif op == "LeakyRelu":
        vals[out] = jnp.where(ins[0] > 0, ins[0],
                              attrs.get("alpha", 0.01) * ins[0])
    elif op == "Sigmoid":
        vals[out] = jax.nn.sigmoid(ins[0])
    elif op == "Tanh":
        vals[out] = jnp.tanh(ins[0])
    elif op == "Erf":
        vals[out] = jax.scipy.special.erf(ins[0])
    elif op == "Sqrt":
        vals[out] = jnp.sqrt(ins[0])
    elif op == "Softmax":
        if opset >= 13:
            vals[out] = jax.nn.softmax(ins[0], axis=attrs.get("axis", -1))
        else:
            # opset <13: flatten to 2D at `axis` (default 1), softmax the
            # trailing block, restore shape
            ax = attrs.get("axis", 1) % ins[0].ndim
            shape = ins[0].shape
            flat = ins[0].reshape(int(np.prod(shape[:ax]) if ax else 1), -1)
            vals[out] = jax.nn.softmax(flat, axis=-1).reshape(shape)
    elif op == "Conv":
        spatial = ins[1].ndim - 2  # kernel is (O, I/g, *spatial) — 1/2/3D
        if not 1 <= spatial <= 3:
            raise NotImplementedError(f"Conv with {spatial} spatial dims")
        strides = attrs.get("strides", [1] * spatial)
        dil = attrs.get("dilations", [1] * spatial)
        pads = _conv_padding(attrs, spatial, ins[0].shape,
                             ins[1].shape[2:], strides)
        chars = "DHW"[3 - spatial:]
        vals[out] = jax.lax.conv_general_dilated(
            ins[0], ins[1], tuple(strides), pads, rhs_dilation=tuple(dil),
            dimension_numbers=("NC" + chars, "OI" + chars, "NC" + chars),
            feature_group_count=int(attrs.get("group", 1)),
            preferred_element_type=jnp.float32)
        if len(ins) > 2 and ins[2] is not None:
            vals[out] = vals[out] + ins[2].reshape(1, -1, *([1] * spatial))
    elif op == "MaxPool":
        vals[out] = _pool(ins[0], jax.lax.max, -jnp.inf, attrs)
    elif op == "AveragePool":
        window, strd, real, full = _pool_cfg(ins[0], attrs)
        pad_cfg = (full if isinstance(full, str)
                   else [(0, 0), (0, 0)] + list(full))
        s = jax.lax.reduce_window(ins[0], 0.0, jax.lax.add, window, strd,
                                  pad_cfg)
        if attrs.get("count_include_pad"):
            # the divisor counts input + REAL padding cells — never the
            # ceil-mode extension (ONNX/torch clip it out): pool a ones
            # array pre-padded with ones over the real pads, zero-padded
            # over only the ceil extension
            if isinstance(real, str) or real == full:
                vals[out] = s / float(np.prod(attrs["kernel_shape"]))
            else:
                ones = jnp.pad(jnp.ones_like(ins[0]),
                               [(0, 0), (0, 0)] + list(real),
                               constant_values=1.0)
                ext = [(0, f[1] - r[1]) for r, f in zip(real, full)]
                n = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add, window, strd,
                    [(0, 0), (0, 0)] + ext)
                vals[out] = s / n
        else:
            n = jax.lax.reduce_window(jnp.ones_like(ins[0]), 0.0,
                                      jax.lax.add, window, strd, pad_cfg)
            vals[out] = s / n
    elif op == "GlobalAveragePool":
        vals[out] = jnp.mean(ins[0], axis=tuple(range(2, ins[0].ndim)),
                             keepdims=True)
    elif op == "BatchNormalization":
        x, gamma, beta, mean, var = ins[:5]
        eps = attrs.get("epsilon", 1e-5)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        vals[out] = (gamma.reshape(shape) * (x - mean.reshape(shape))
                     / jnp.sqrt(var.reshape(shape) + eps)
                     + beta.reshape(shape))
    elif op == "Flatten":
        ax = attrs.get("axis", 1)
        vals[out] = ins[0].reshape(
            int(np.prod(ins[0].shape[:ax])) if ax else 1, -1)
    elif op == "Reshape":
        shape = [int(s) for s in np.asarray(ins[1])]
        vals[out] = ins[0].reshape(
            [ins[0].shape[i] if s == 0 else s for i, s in enumerate(shape)])
    elif op == "Transpose":
        vals[out] = jnp.transpose(ins[0], attrs.get("perm"))
    elif op == "Concat":
        vals[out] = jnp.concatenate(ins, axis=attrs.get("axis", 0))
    elif op == "Gather":
        vals[out] = jnp.take(ins[0], ins[1].astype(jnp.int32),
                             axis=attrs.get("axis", 0))
    elif op == "Unsqueeze":
        axes = attrs.get("axes") or [int(a) for a in np.asarray(ins[1])]
        y = ins[0]
        for a in sorted(axes):
            y = jnp.expand_dims(y, a)
        vals[out] = y
    elif op == "Squeeze":
        axes = attrs.get("axes") or ([int(a) for a in np.asarray(ins[1])]
                                     if len(ins) > 1 and ins[1] is not None
                                     else None)
        vals[out] = jnp.squeeze(ins[0],
                                axis=tuple(axes) if axes else None)
    elif op == "ReduceMean":
        # axes: attribute (opset <18) or second input (opset >=18)
        axes = attrs.get("axes") or ([int(a) for a in np.asarray(ins[1])]
                                     if len(ins) > 1 and ins[1] is not None
                                     else None)
        vals[out] = jnp.mean(ins[0], axis=tuple(axes) if axes else None,
                             keepdims=bool(attrs.get("keepdims", 1)))
    elif op == "Clip":
        lo = ins[1] if len(ins) > 1 and ins[1] is not None else attrs.get("min")
        hi = ins[2] if len(ins) > 2 and ins[2] is not None else attrs.get("max")
        vals[out] = jnp.clip(ins[0], lo, hi)
    elif op == "Identity":
        vals[out] = ins[0]
    elif op == "Dropout":
        ratio = (float(np.asarray(ins[1]))
                 if len(ins) > 1 and ins[1] is not None
                 else attrs.get("ratio", 0.5))
        if training and rng is not None and ratio > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - ratio, ins[0].shape)
            vals[out] = jnp.where(keep, ins[0] / (1.0 - ratio), 0.0)
        else:
            vals[out] = ins[0]
    elif op == "Constant":
        if "value" in attrs:
            vals[out] = jnp.asarray(attrs["value"])
        elif "value_float" in attrs:
            vals[out] = jnp.asarray(attrs["value_float"], jnp.float32)
        elif "value_int" in attrs:
            vals[out] = jnp.asarray(attrs["value_int"], jnp.int64)
        elif "value_floats" in attrs:
            vals[out] = jnp.asarray(attrs["value_floats"], jnp.float32)
        elif "value_ints" in attrs:
            vals[out] = jnp.asarray(attrs["value_ints"], jnp.int64)
        else:
            raise NotImplementedError(
                f"Constant node {node['name']!r} has none of value/"
                f"value_float(s)/value_int(s); got {sorted(attrs)}")
    else:
        raise NotImplementedError(f"ONNX op {op!r} not supported "
                                  f"(node {node['name']!r})")


# ---------------------------------------------------------------------------
# the Layer
# ---------------------------------------------------------------------------

# (op, input position) pairs whose initializer operand is STRUCTURE, not a
# weight: shape/axes/index vectors, Clip bounds, BN running statistics
_STRUCTURAL_INPUTS = {("Reshape", 1), ("Unsqueeze", 1), ("Squeeze", 1),
                      ("ReduceMean", 1),
                      ("Gather", 1), ("Clip", 1), ("Clip", 2),
                      ("BatchNormalization", 3), ("BatchNormalization", 4),
                      ("Dropout", 1)}


class OnnxNet(Layer):
    """An ONNX graph as a Layer: float weight initializers are params
    (fine-tunable); shape/axes/index/statistic initializers stay host
    constants so they never hit the optimizer or trace as Tracers."""

    def __init__(self, graph: Dict[str, Any], **kwargs):
        super().__init__(**kwargs)
        self.nodes = graph["nodes"]
        self.output_names = graph["outputs"]
        self.opset = graph.get("opset", 13)
        # graph inputs that are NOT initializers are the runtime feeds
        self.feed_names = [n for n in graph["inputs"]
                           if n not in graph["initializers"]]
        # only a node's first output is computed; fail at load (not with a
        # bare KeyError mid-call) if a secondary output is ever consumed
        consumed = set(self.output_names)
        for node in self.nodes:
            consumed.update(n for n in node["inputs"] if n)
        for node in self.nodes:
            for extra in node["outputs"][1:]:
                if extra and extra in consumed:
                    raise NotImplementedError(
                        f"node {node['name']!r} ({node['op']}): secondary "
                        f"output {extra!r} is consumed, but only the first "
                        f"output of each node is computed")
        structural = set()
        for node in self.nodes:
            for pos, name in enumerate(node["inputs"]):
                if (node["op"], pos) in _STRUCTURAL_INPUTS:
                    structural.add(name)
        self.consts = {n: np.asarray(a)
                       for n, a in graph["initializers"].items()
                       if n in structural
                       or not np.issubdtype(np.asarray(a).dtype, np.floating)}
        self._weights: Optional[Dict[str, np.ndarray]] = {
            n: np.asarray(a) for n, a in graph["initializers"].items()
            if n not in self.consts}
        self._built_params: Optional[Dict[str, jnp.ndarray]] = None

    def build(self, rng, input_shape=None):
        # move (not copy) the imported weights onto the device: the host
        # numpy copies are released so large models aren't held twice
        if self._built_params is None:
            self._built_params = {n: jnp.asarray(a)
                                  for n, a in self._weights.items()}
            self._weights = None
        return self._built_params

    def initial_state(self, input_shape=None):
        return {}

    def call(self, params, x, *, training=False, rng=None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        if len(xs) != len(self.feed_names):
            raise ValueError(f"expected {len(self.feed_names)} inputs "
                             f"({self.feed_names}), got {len(xs)}")
        vals: Dict[str, Any] = dict(self.consts)
        vals.update(params)
        vals.update(zip(self.feed_names, xs))
        for i, node in enumerate(self.nodes):
            node_rng = (jax.random.fold_in(rng, i)
                        if rng is not None else None)
            _run_node(node, vals, training, node_rng, self.opset)
        outs = [vals[n] for n in self.output_names]
        return outs[0] if len(outs) == 1 else outs


class OnnxLoader:
    """``OnnxLoader.load(path)`` — reference class name parity."""

    @staticmethod
    def load(path: str) -> OnnxNet:
        return load_onnx(path)


def load_onnx(path: str) -> OnnxNet:
    with open(path, "rb") as f:
        graph = _decode_model(f.read())
    return OnnxNet(graph)
