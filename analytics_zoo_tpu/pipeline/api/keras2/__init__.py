"""Keras-2 style API — parity with ``pyzoo/zoo/pipeline/api/keras2`` (the
reference maintains a second layer namespace with Keras-2 argument
conventions: ``units``/``filters``/``kernel_size``/``rate``/``padding``/
``use_bias``/``kernel_initializer`` instead of Keras-1's ``output_dim``/
``nb_filter``/``p``/``border_mode``/``init``).

Here every keras2 symbol is a thin constructor adapter over the SAME layer
classes as ``api.keras.layers`` — one graph engine, two argument dialects —
so keras2-built models train, shard, and serialize identically.
"""

from . import layers  # noqa: F401
