"""Keras-2 argument-dialect constructors (see package docstring).

Each function returns a configured layer from ``api.keras.layers`` — the
keras2 namespace adds NO new layer semantics, exactly like the reference
(its keras2 classes call the same BigDL modules with renamed args,
``pyzoo/zoo/pipeline/api/keras2/layers/core.py:26-160``).
"""

from __future__ import annotations

from ..keras import layers as K1
from ..keras.engine import Input, Model, Sequential  # noqa: F401 (re-export)

__all__ = [
    "Input", "Model", "Sequential",
    "Dense", "Activation", "Dropout", "Flatten", "Reshape", "Permute",
    "RepeatVector", "Masking", "Embedding",
    "Conv1D", "Conv2D", "Conv3D", "SeparableConv2D", "Conv2DTranspose",
    "LocallyConnected1D", "LocallyConnected2D",
    "Cropping1D", "Cropping2D", "Cropping3D",
    "UpSampling1D", "UpSampling2D", "UpSampling3D",
    "ZeroPadding1D", "ZeroPadding2D", "ZeroPadding3D",
    "MaxPooling1D", "MaxPooling2D", "MaxPooling3D",
    "AveragePooling1D", "AveragePooling2D", "AveragePooling3D",
    "GlobalMaxPooling1D", "GlobalMaxPooling2D", "GlobalMaxPooling3D",
    "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalAveragePooling3D",
    "BatchNormalization", "LayerNormalization",
    "LSTM", "GRU", "SimpleRNN", "Bidirectional", "TimeDistributed",
    "LeakyReLU", "ELU", "PReLU", "ThresholdedReLU", "Softmax",
    "GaussianNoise", "GaussianDropout",
    "SpatialDropout1D", "SpatialDropout2D", "SpatialDropout3D",
    "add", "multiply", "average", "maximum", "minimum", "concatenate", "dot",
]


from ..keras.layers._shapes import pair as _pair, triple as _triple  # noqa: E402


# --- core ------------------------------------------------------------------

def Dense(units, activation=None, use_bias=True,
          kernel_initializer="glorot_uniform", input_dim=None,
          input_shape=None, **kwargs):
    if input_dim is not None:
        input_shape = (input_dim,)
    return K1.Dense(units, init=kernel_initializer, activation=activation,
                    bias=use_bias, input_shape=input_shape, **kwargs)


def Activation(activation, **kwargs):
    return K1.Activation(activation, **kwargs)


def Dropout(rate, **kwargs):
    return K1.Dropout(rate, **kwargs)


def Flatten(**kwargs):
    return K1.Flatten(**kwargs)


def Reshape(target_shape, **kwargs):
    return K1.Reshape(target_shape, **kwargs)


def Permute(dims, **kwargs):
    return K1.Permute(dims, **kwargs)


def RepeatVector(n, **kwargs):
    return K1.RepeatVector(n, **kwargs)


def Masking(mask_value=0.0, **kwargs):
    return K1.Masking(mask_value, **kwargs)


def Embedding(input_dim, output_dim, input_length=None, **kwargs):
    if input_length is not None:
        kwargs.setdefault("input_shape", (input_length,))
    return K1.Embedding(input_dim, output_dim, **kwargs)


# --- convolution -----------------------------------------------------------

def Conv1D(filters, kernel_size, strides=1, padding="valid",
           dilation_rate=1, activation=None, use_bias=True,
           kernel_initializer="glorot_uniform", **kwargs):
    return K1.Convolution1D(filters, kernel_size, init=kernel_initializer,
                            activation=activation, border_mode=padding,
                            subsample_length=strides,
                            dilation_rate=dilation_rate, bias=use_bias,
                            **kwargs)


def Conv2D(filters, kernel_size, strides=(1, 1), padding="valid",
           dilation_rate=(1, 1), activation=None, use_bias=True,
           kernel_initializer="glorot_uniform", **kwargs):
    kh, kw = _pair(kernel_size)
    return K1.Convolution2D(filters, kh, kw, init=kernel_initializer,
                            activation=activation, border_mode=padding,
                            subsample=_pair(strides),
                            dilation=_pair(dilation_rate), bias=use_bias,
                            **kwargs)


def Conv3D(filters, kernel_size, strides=(1, 1, 1), padding="valid",
           activation=None, use_bias=True,
           kernel_initializer="glorot_uniform", **kwargs):
    k1, k2, k3 = _triple(kernel_size)
    return K1.Convolution3D(filters, k1, k2, k3, init=kernel_initializer,
                            activation=activation, border_mode=padding,
                            subsample=_triple(strides), bias=use_bias,
                            **kwargs)


def SeparableConv2D(filters, kernel_size, strides=(1, 1), padding="valid",
                    depth_multiplier=1, activation=None, use_bias=True,
                    **kwargs):
    kh, kw = _pair(kernel_size)
    return K1.SeparableConvolution2D(filters, kh, kw, activation=activation,
                                     border_mode=padding,
                                     subsample=_pair(strides),
                                     depth_multiplier=depth_multiplier,
                                     bias=use_bias, **kwargs)


def Conv2DTranspose(filters, kernel_size, strides=(1, 1), padding="valid",
                    activation=None, use_bias=True, **kwargs):
    if padding != "valid":
        raise ValueError("Conv2DTranspose supports only padding='valid' "
                         "(like the reference's Deconvolution2D)")
    kh, kw = _pair(kernel_size)
    return K1.Deconvolution2D(filters, kh, kw, activation=activation,
                              subsample=_pair(strides), bias=use_bias,
                              **kwargs)


def LocallyConnected1D(filters, kernel_size, activation=None, use_bias=True,
                       **kwargs):
    return K1.LocallyConnected1D(filters, kernel_size, activation=activation,
                                 bias=use_bias, **kwargs)


def LocallyConnected2D(filters, kernel_size, strides=(1, 1), activation=None,
                       use_bias=True, **kwargs):
    kh, kw = _pair(kernel_size)
    return K1.LocallyConnected2D(filters, kh, kw, activation=activation,
                                 subsample=_pair(strides), bias=use_bias,
                                 **kwargs)


def Cropping1D(cropping=(1, 1), **kwargs):
    return K1.Cropping1D(cropping, **kwargs)


def Cropping2D(cropping=((0, 0), (0, 0)), **kwargs):
    return K1.Cropping2D(cropping, **kwargs)


def Cropping3D(cropping=((1, 1), (1, 1), (1, 1)), **kwargs):
    return K1.Cropping3D(cropping, **kwargs)


def UpSampling1D(size=2, **kwargs):
    return K1.UpSampling1D(size, **kwargs)


def UpSampling2D(size=(2, 2), **kwargs):
    return K1.UpSampling2D(_pair(size), **kwargs)


def UpSampling3D(size=(2, 2, 2), **kwargs):
    return K1.UpSampling3D(_triple(size), **kwargs)


def ZeroPadding1D(padding=1, **kwargs):
    return K1.ZeroPadding1D(padding, **kwargs)


def ZeroPadding2D(padding=(1, 1), **kwargs):
    return K1.ZeroPadding2D(_pair(padding), **kwargs)


def ZeroPadding3D(padding=(1, 1, 1), **kwargs):
    return K1.ZeroPadding3D(_triple(padding), **kwargs)


# --- pooling ---------------------------------------------------------------

def MaxPooling1D(pool_size=2, strides=None, padding="valid", **kwargs):
    return K1.MaxPooling1D(pool_size, strides, border_mode=padding, **kwargs)


def MaxPooling2D(pool_size=(2, 2), strides=None, padding="valid", **kwargs):
    return K1.MaxPooling2D(_pair(pool_size),
                           _pair(strides) if strides is not None else None,
                           border_mode=padding, **kwargs)


def MaxPooling3D(pool_size=(2, 2, 2), strides=None, padding="valid",
                 **kwargs):
    return K1.MaxPooling3D(_triple(pool_size),
                           _triple(strides) if strides is not None else None,
                           border_mode=padding, **kwargs)


def AveragePooling1D(pool_size=2, strides=None, padding="valid", **kwargs):
    return K1.AveragePooling1D(pool_size, strides, border_mode=padding,
                               **kwargs)


def AveragePooling2D(pool_size=(2, 2), strides=None, padding="valid",
                     **kwargs):
    return K1.AveragePooling2D(_pair(pool_size),
                               _pair(strides) if strides is not None else None,
                               border_mode=padding, **kwargs)


def AveragePooling3D(pool_size=(2, 2, 2), strides=None, padding="valid",
                     **kwargs):
    return K1.AveragePooling3D(
        _triple(pool_size),
        _triple(strides) if strides is not None else None,
        border_mode=padding, **kwargs)


def GlobalMaxPooling1D(**kwargs):
    return K1.GlobalMaxPooling1D(**kwargs)


def GlobalMaxPooling2D(**kwargs):
    return K1.GlobalMaxPooling2D(**kwargs)


def GlobalMaxPooling3D(**kwargs):
    return K1.GlobalMaxPooling3D(**kwargs)


def GlobalAveragePooling1D(**kwargs):
    return K1.GlobalAveragePooling1D(**kwargs)


def GlobalAveragePooling2D(**kwargs):
    return K1.GlobalAveragePooling2D(**kwargs)


def GlobalAveragePooling3D(**kwargs):
    return K1.GlobalAveragePooling3D(**kwargs)


# --- normalization ---------------------------------------------------------

def BatchNormalization(momentum=0.99, epsilon=1e-3, **kwargs):
    return K1.BatchNormalization(epsilon=epsilon, momentum=momentum, **kwargs)


def LayerNormalization(epsilon=1e-5, **kwargs):
    return K1.LayerNorm(epsilon=epsilon, **kwargs)


# --- recurrent -------------------------------------------------------------

def LSTM(units, activation="tanh", recurrent_activation="hard_sigmoid",
         return_sequences=False, **kwargs):
    return K1.LSTM(units, activation=activation,
                   inner_activation=recurrent_activation,
                   return_sequences=return_sequences, **kwargs)


def GRU(units, activation="tanh", recurrent_activation="hard_sigmoid",
        return_sequences=False, **kwargs):
    return K1.GRU(units, activation=activation,
                  inner_activation=recurrent_activation,
                  return_sequences=return_sequences, **kwargs)


def SimpleRNN(units, activation="tanh", return_sequences=False, **kwargs):
    return K1.SimpleRNN(units, activation=activation,
                        return_sequences=return_sequences, **kwargs)


def Bidirectional(layer, merge_mode="concat", **kwargs):
    return K1.Bidirectional(layer, merge_mode=merge_mode, **kwargs)


def TimeDistributed(layer, **kwargs):
    return K1.TimeDistributed(layer, **kwargs)


# --- activations / noise ---------------------------------------------------

def LeakyReLU(alpha=0.3, **kwargs):
    return K1.LeakyReLU(alpha, **kwargs)


def ELU(alpha=1.0, **kwargs):
    return K1.ELU(alpha, **kwargs)


def PReLU(**kwargs):
    return K1.PReLU(**kwargs)


def ThresholdedReLU(theta=1.0, **kwargs):
    return K1.ThresholdedReLU(theta, **kwargs)


def Softmax(**kwargs):
    return K1.Softmax(**kwargs)


def GaussianNoise(stddev, **kwargs):
    return K1.GaussianNoise(stddev, **kwargs)


def GaussianDropout(rate, **kwargs):
    return K1.GaussianDropout(rate, **kwargs)


def SpatialDropout1D(rate=0.5, **kwargs):
    return K1.SpatialDropout1D(rate, **kwargs)


def SpatialDropout2D(rate=0.5, **kwargs):
    return K1.SpatialDropout2D(rate, **kwargs)


def SpatialDropout3D(rate=0.5, **kwargs):
    return K1.SpatialDropout3D(rate, **kwargs)


# --- functional merges -----------------------------------------------------

def add(inputs, **kwargs):
    return K1.merge(inputs, mode="sum", **kwargs)


def multiply(inputs, **kwargs):
    return K1.merge(inputs, mode="mul", **kwargs)


def average(inputs, **kwargs):
    return K1.merge(inputs, mode="ave", **kwargs)


def maximum(inputs, **kwargs):
    return K1.merge(inputs, mode="max", **kwargs)


def minimum(inputs, **kwargs):
    return K1.merge(inputs, mode="min", **kwargs)


def concatenate(inputs, axis=-1, **kwargs):
    return K1.merge(inputs, mode="concat", concat_axis=axis, **kwargs)


def dot(inputs, **kwargs):
    return K1.merge(inputs, mode="dot", **kwargs)
