"""TFNet — load a frozen TensorFlow ``GraphDef`` (.pb) and run it as a
native JAX ``Layer``.

Reference parity: ``pipeline/api/net/TFNet.scala:53-56`` wraps a frozen TF
graph as a BigDL module via a libtensorflow JNI session (``TFNet.scala:
158-162``); the Python side is ``pyzoo/zoo/pipeline/api/net/tfnet.py:51``.
Here there is no TF runtime at all (SURVEY §2.3: "graphs become
jit-compiled JAX fns"): the GraphDef protobuf is parsed with the in-repo
wire codec (``utils/proto.py``) and each node maps to a jnp op, so the
whole graph jits, fuses, shards, and — because float Const weights become
layer params — fine-tunes under the standard train step, which the
reference's frozen ``TFNet`` cannot do unless the graph ships gradient ops
(``TFNet.scala:72-77``).

Supported op set mirrors what the reference's TFNet examples feed it
(frozen classifier/backbone graphs): MatMul/Conv2D/DepthwiseConv2d +
BiasAdd, FusedBatchNorm(V3) (inference form), pooling, the elementwise/
activation family, reduce/shape ops, ConcatV2/Pack/Transpose/Pad/Gather,
Cast/ArgMax. Unsupported ops fail at load time with the op name.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.proto import parse_fields, parse_varint
from .keras.engine import Layer

__all__ = ["TFNet", "load_tf"]

# tensorflow DataType enum → numpy (DT_BFLOAT16=14 widens to f32 on the
# host via an explicit bit-pattern conversion in _decode_tensor)
_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
           5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
           14: np.float32, 19: np.float16}


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _as_int(payload: bytes) -> int:
    v, _ = parse_varint(payload, 0)
    return v


def _packed_ints(payload: bytes, wt: int) -> List[int]:
    if wt == 2:
        out, i = [], 0
        while i < len(payload):
            v, i = parse_varint(payload, i)
            out.append(_signed(v))
        return out
    return [_signed(_as_int(payload))]


# ---------------------------------------------------------------------------
# GraphDef decoding (tensorflow/core/framework/{graph,node_def,attr_value,
# tensor,tensor_shape}.proto subset)
# ---------------------------------------------------------------------------

def _decode_shape(buf: bytes) -> List[int]:
    dims: List[int] = []
    for num, wt, payload in parse_fields(buf):
        if num == 2:  # Dim
            size = -1
            for n2, _, p2 in parse_fields(payload):
                if n2 == 1:
                    size = _signed(_as_int(p2))
            dims.append(size)
    return dims


def _bits_to_float(vals: List[int], code: int) -> np.ndarray:
    """half_val holds raw bit patterns for DT_HALF and DT_BFLOAT16."""
    u16 = np.asarray(vals, np.uint16)
    if code == 14:  # bfloat16: bits are the top half of a float32
        return (u16.astype(np.uint32) << 16).view(np.float32)
    return u16.view(np.float16).astype(np.float32)


def _decode_tensor(buf: bytes) -> np.ndarray:
    # field numbers per tensorflow/core/framework/tensor.proto:
    # dtype=1 shape=2 tensor_content=4 half_val=13 float_val=5
    # double_val=6 int_val=7 string_val=8 int64_val=10 bool_val=11
    code = 1
    shape: List[int] = []
    content: Optional[bytes] = None
    floats: List[float] = []
    ints: List[int] = []
    doubles: List[float] = []
    bools: List[bool] = []
    halves: List[int] = []
    strings: List[bytes] = []
    for num, wt, payload in parse_fields(buf):
        if num == 1:
            code = _as_int(payload)
            # DT_STRING (7) decodes to an object array so Saver-machinery
            # consts (file patterns, slice names) survive GRAPH DECODE;
            # executing one still fails at the consuming op
            if code != 7 and code not in _DTYPES:
                raise NotImplementedError(f"TensorProto dtype {code}")
        elif num == 2:
            shape = _decode_shape(payload)
        elif num == 8:               # string_val
            strings.append(bytes(payload))
        elif num == 4:
            content = payload
        elif num == 5:               # float_val
            if wt == 2:
                floats.extend(struct.unpack(f"<{len(payload) // 4}f", payload))
            else:
                floats.append(struct.unpack("<f", payload)[0])
        elif num == 6:               # double_val
            if wt == 2:
                doubles.extend(struct.unpack(f"<{len(payload) // 8}d", payload))
            else:
                doubles.append(struct.unpack("<d", payload)[0])
        elif num in (7, 10):         # int_val / int64_val
            ints.extend(_packed_ints(payload, wt))
        elif num == 11:              # bool_val
            bools.extend(bool(v) for v in _packed_ints(payload, wt))
        elif num == 13:              # half_val (f16/bf16 bit patterns)
            halves.extend(_packed_ints(payload, wt))
    if code == 7:
        return np.asarray(strings, dtype=object).reshape(
            shape if shape else (len(strings),) if len(strings) != 1 else ())
    dtype = _DTYPES[code]
    n = int(np.prod(shape)) if shape else 1
    if content is not None:
        if code == 14:
            u16 = np.frombuffer(content, dtype=np.uint16)
            arr = (u16.astype(np.uint32) << 16).view(np.float32).copy()
        else:
            arr = np.frombuffer(content, dtype=dtype).copy()
    elif halves:
        arr = _bits_to_float(halves, code).astype(dtype)
    elif floats:
        arr = np.asarray(floats, dtype)
    elif doubles:
        arr = np.asarray(doubles, dtype)
    elif ints:
        arr = np.asarray(ints, dtype)
    elif bools:
        arr = np.asarray(bools, dtype)
    else:
        arr = np.zeros(0, dtype)
    if arr.size == 1 and n > 1:      # TF scalar-splat encoding
        arr = np.full(n, arr.reshape(-1)[0], dtype)
    return arr.reshape(shape)


def _decode_attr(buf: bytes) -> Any:
    """AttrValue → python value (s/i/f/b/type/shape/tensor/list)."""
    for num, wt, payload in parse_fields(buf):
        if num == 2:
            return payload.decode("utf-8", "replace")
        if num == 3:
            return _signed(_as_int(payload))
        if num == 4:
            return struct.unpack("<f", payload[:4])[0]
        if num == 5:
            return bool(_as_int(payload))
        if num == 6:
            return ("dtype", _as_int(payload))
        if num == 7:
            return _decode_shape(payload)
        if num == 8:
            return _decode_tensor(payload)
        if num == 1:  # ListValue
            ints: List[int] = []
            strs: List[str] = []
            floats: List[float] = []
            for n2, wt2, p2 in parse_fields(payload):
                if n2 == 2:
                    strs.append(p2.decode("utf-8", "replace"))
                elif n2 in (3, 6):
                    ints.extend(_packed_ints(p2, wt2))
                elif n2 == 4:
                    if wt2 == 2:
                        floats.extend(
                            struct.unpack(f"<{len(p2) // 4}f", p2))
                    else:
                        floats.append(struct.unpack("<f", p2)[0])
            return strs or floats or ints
    return None


def _decode_node(buf: bytes) -> Dict[str, Any]:
    node = {"name": "", "op": "", "inputs": [], "attrs": {}}
    for num, wt, payload in parse_fields(buf):
        if num == 1:
            node["name"] = payload.decode("utf-8")
        elif num == 2:
            node["op"] = payload.decode("utf-8")
        elif num == 3:
            node["inputs"].append(payload.decode("utf-8"))
        elif num == 5:  # attr map entry
            key, val = "", None
            for n2, _, p2 in parse_fields(payload):
                if n2 == 1:
                    key = p2.decode("utf-8")
                elif n2 == 2:
                    val = _decode_attr(p2)
            node["attrs"][key] = val
    return node


def _decode_graph(buf: bytes) -> List[Dict[str, Any]]:
    nodes = []
    for num, wt, payload in parse_fields(buf):
        if num == 1:
            nodes.append(_decode_node(payload))
    return nodes


# ---------------------------------------------------------------------------
# op semantics
# ---------------------------------------------------------------------------

def _same_pad(in_size: int, k: int, s: int) -> Tuple[int, int]:
    out = -(-in_size // s)
    pad = max(0, (out - 1) * s + k - in_size)
    return pad // 2, pad - pad // 2


def _conv_pads(x, kh, kw, sh, sw, padding):
    if padding == "VALID":
        return ((0, 0), (0, 0))
    return (_same_pad(x.shape[1], kh, sh), _same_pad(x.shape[2], kw, sw))


def _conv2d(x, w, attrs, *, depthwise=False):
    sh, sw = attrs.get("strides", [1, 1, 1, 1])[1:3]
    dil = attrs.get("dilations", [1, 1, 1, 1])[1:3]
    if attrs.get("data_format", "NHWC") != "NHWC":
        raise NotImplementedError("only NHWC Conv2D is supported")
    pads = _conv_pads(x, w.shape[0] * dil[0] - dil[0] + 1,
                      w.shape[1] * dil[1] - dil[1] + 1, sh, sw,
                      attrs.get("padding", "SAME"))
    groups = w.shape[2] if depthwise else 1
    if depthwise:
        # HWCM -> HWC(M) with feature_group_count=C
        w = w.reshape(w.shape[0], w.shape[1], 1, -1)
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(sh, sw), padding=pads,
        rhs_dilation=tuple(dil), feature_group_count=groups,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _pool2d(x, attrs, op):
    if attrs.get("data_format", "NHWC") != "NHWC":
        raise NotImplementedError(f"only NHWC {op} is supported")
    kh, kw = attrs.get("ksize", [1, 2, 2, 1])[1:3]
    sh, sw = attrs.get("strides", [1, 2, 2, 1])[1:3]
    pads = ((0, 0),) + _conv_pads(x, kh, kw, sh, sw,
                                  attrs.get("padding", "VALID")) + ((0, 0),)
    if op == "MaxPool":
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, kh, kw, 1), (1, sh, sw, 1), pads)
    s = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, kh, kw, 1), (1, sh, sw, 1), pads)
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    cnt = jax.lax.reduce_window(
        ones, 0.0, jax.lax.add, (1, kh, kw, 1), (1, sh, sw, 1), pads)
    return s / cnt


def _fused_bn(xs, attrs):
    if attrs.get("data_format", "NHWC") != "NHWC":
        raise NotImplementedError("only NHWC FusedBatchNorm is supported")
    x, scale, offset, mean, var = xs
    eps = attrs.get("epsilon", 1e-3) or 1e-3
    if attrs.get("is_training", False):
        raise NotImplementedError(
            "FusedBatchNorm with is_training=True (frozen graphs only)")
    inv = jax.lax.rsqrt(var + eps) * scale
    return x * inv + (offset - mean * inv)


_ELEMENTWISE = {
    "Add": jnp.add, "AddV2": jnp.add, "Sub": jnp.subtract,
    "Mul": jnp.multiply, "RealDiv": jnp.divide, "Div": jnp.divide,
    "Maximum": jnp.maximum, "Minimum": jnp.minimum, "Pow": jnp.power,
    "SquaredDifference": lambda a, b: jnp.square(a - b),
    "FloorDiv": jnp.floor_divide, "Mod": jnp.mod, "FloorMod": jnp.mod,
    "Greater": jnp.greater, "GreaterEqual": jnp.greater_equal,
    "Less": jnp.less, "LessEqual": jnp.less_equal, "Equal": jnp.equal,
    "LogicalAnd": jnp.logical_and, "LogicalOr": jnp.logical_or,
}

_UNARY = {
    "Relu": jax.nn.relu, "Relu6": jax.nn.relu6, "Elu": jax.nn.elu,
    "Selu": jax.nn.selu, "Softplus": jax.nn.softplus,
    "Softsign": jax.nn.soft_sign, "Sigmoid": jax.nn.sigmoid,
    "Tanh": jnp.tanh, "Exp": jnp.exp, "Log": jnp.log, "Neg": jnp.negative,
    "Abs": jnp.abs, "Square": jnp.square, "Sqrt": jnp.sqrt,
    "Rsqrt": jax.lax.rsqrt, "Erf": jax.scipy.special.erf,
    "Floor": jnp.floor, "Ceil": jnp.ceil, "Round": jnp.round,
    "Identity": lambda x: x, "StopGradient": jax.lax.stop_gradient,
    # resource-variable read: the SavedModel importer turns VarHandleOp
    # into a Const carrying the restored value, so the read is identity
    "ReadVariableOp": lambda x: x,
    "Reciprocal": jnp.reciprocal, "LogicalNot": jnp.logical_not,
}

_REDUCE = {"Mean": jnp.mean, "Sum": jnp.sum, "Max": jnp.max, "Min": jnp.min,
           "Prod": jnp.prod, "All": jnp.all, "Any": jnp.any}

# (op, input position) pairs whose values must stay host constants
_STRUCTURAL = {("Reshape", 1), ("ConcatV2", -1), ("Transpose", 1),
               ("Pad", 1), ("PadV2", 1), ("ExpandDims", 1), ("Mean", 1),
               ("Sum", 1), ("Max", 1), ("Min", 1), ("Prod", 1), ("All", 1),
               ("Any", 1), ("ArgMax", 1), ("GatherV2", 2),
               ("Tile", 1), ("Fill", 0), ("StridedSlice", 1),
               ("StridedSlice", 2), ("StridedSlice", 3)}

# (op, input position) pairs pinned NON-trainable even under trainable=True:
# FusedBatchNorm's moving mean/variance (positions 3, 4) are inference-mode
# STATISTICS — updating them by gradient descent silently diverges from
# frozen-BN fine-tune semantics (scale/offset at 1, 2 stay trainable, as in
# a standard BN fine-tune)
_FROZEN_STATS = {("FusedBatchNorm", 3), ("FusedBatchNorm", 4),
                 ("FusedBatchNormV2", 3), ("FusedBatchNormV2", 4),
                 ("FusedBatchNormV3", 3), ("FusedBatchNormV3", 4)}

# every op _run_node dispatches on; the load-time coverage check uses this
_SUPPORTED_OPS = (set(_UNARY) | set(_ELEMENTWISE) | set(_REDUCE) | {
    "AddN", "LeakyRelu", "Softmax", "LogSoftmax", "MatMul", "BatchMatMul",
    "BatchMatMulV2", "BiasAdd", "Conv2D", "DepthwiseConv2dNative",
    "MaxPool", "AvgPool", "FusedBatchNorm", "FusedBatchNormV2",
    "FusedBatchNormV3", "Reshape", "Squeeze", "ExpandDims", "ConcatV2",
    "Pack", "Transpose", "Pad", "PadV2", "GatherV2", "Gather", "Tile",
    "Cast", "ArgMax", "Shape", "Rank", "StridedSlice", "Fill"})


def _static(v, what):
    if isinstance(v, jnp.ndarray):
        raise NotImplementedError(
            f"{what} must be a graph constant, found a traced tensor")
    return np.asarray(v)


def _static_scalar(v, what) -> int:
    return int(_static(v, what).reshape(-1)[0])


def _run_node(node, vals):
    op = node["op"]
    attrs = node["attrs"]
    names = [n for n in node["inputs"] if not n.startswith("^")]
    xs = [vals[n] for n in names]  # producers register both name and name:0

    if op in _UNARY:
        out = _UNARY[op](xs[0])
    elif op in _ELEMENTWISE:
        out = _ELEMENTWISE[op](xs[0], xs[1])
    elif op == "AddN":
        out = xs[0]
        for a in xs[1:]:
            out = out + a
    elif op == "LeakyRelu":
        out = jax.nn.leaky_relu(xs[0], attrs.get("alpha", 0.2))
    elif op == "Softmax":
        out = jax.nn.softmax(xs[0], axis=-1)
    elif op == "LogSoftmax":
        out = jax.nn.log_softmax(xs[0], axis=-1)
    elif op == "MatMul":
        a = xs[0].T if attrs.get("transpose_a") else xs[0]
        b = xs[1].T if attrs.get("transpose_b") else xs[1]
        out = jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(
            jnp.result_type(xs[0]))
    elif op == "BatchMatMulV2" or op == "BatchMatMul":
        a = jnp.swapaxes(xs[0], -1, -2) if attrs.get("adj_x") else xs[0]
        b = jnp.swapaxes(xs[1], -1, -2) if attrs.get("adj_y") else xs[1]
        out = jnp.matmul(a, b)
    elif op == "BiasAdd":
        if attrs.get("data_format", "NHWC") == "NCHW" and xs[0].ndim == 4:
            out = xs[0] + xs[1].reshape(1, -1, 1, 1)
        else:
            out = xs[0] + xs[1]
    elif op == "Conv2D":
        out = _conv2d(xs[0], xs[1], attrs)
    elif op == "DepthwiseConv2dNative":
        out = _conv2d(xs[0], xs[1], attrs, depthwise=True)
    elif op in ("MaxPool", "AvgPool"):
        out = _pool2d(xs[0], attrs, op)
    elif op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
        out = _fused_bn(xs, attrs)
    elif op in _REDUCE:
        axes = tuple(int(a) for a in
                     np.atleast_1d(_static(xs[1], f"{op} axes")))
        out = _REDUCE[op](xs[0], axis=axes or None,
                          keepdims=bool(attrs.get("keep_dims", False)))
    elif op == "Reshape":
        out = jnp.reshape(
            xs[0], tuple(int(d) for d in _static(xs[1], "Reshape shape")))
    elif op == "Squeeze":
        dims = attrs.get("squeeze_dims") or None
        out = jnp.squeeze(xs[0], axis=tuple(dims) if dims else None)
    elif op == "ExpandDims":
        out = jnp.expand_dims(
            xs[0], _static_scalar(xs[1], "ExpandDims axis"))
    elif op == "ConcatV2":
        axis = _static_scalar(xs[-1], "ConcatV2 axis")
        out = jnp.concatenate(xs[:-1], axis=axis)
    elif op == "Pack":
        out = jnp.stack(xs, axis=attrs.get("axis", 0))
    elif op == "Transpose":
        out = jnp.transpose(
            xs[0], tuple(int(p) for p in _static(xs[1], "Transpose perm")))
    elif op in ("Pad", "PadV2"):
        pads = [tuple(int(v) for v in row)
                for row in _static(xs[1], "Pad paddings")]
        cv = float(np.asarray(xs[2]).reshape(-1)[0]) if len(xs) > 2 else 0.0
        out = jnp.pad(xs[0], pads, constant_values=cv)
    elif op == "GatherV2" or op == "Gather":
        axis = (_static_scalar(xs[2], "Gather axis")
                if len(xs) > 2 else 0)
        out = jnp.take(xs[0], jnp.asarray(xs[1]).astype(jnp.int32),
                       axis=axis)
    elif op == "Tile":
        out = jnp.tile(
            xs[0], tuple(int(v) for v in _static(xs[1], "Tile multiples")))
    elif op == "Cast":
        code = attrs.get("DstT")
        code = code[1] if isinstance(code, tuple) else code
        out = xs[0].astype(_DTYPES[code])
    elif op == "ArgMax":
        out = jnp.argmax(
            xs[0], axis=_static_scalar(xs[1], "ArgMax axis")).astype(jnp.int64)
    elif op == "Shape":
        out = np.asarray(xs[0].shape, np.int32)
    elif op == "Rank":
        out = np.asarray(np.ndim(xs[0]), np.int32)
    elif op == "StridedSlice":
        out = _strided_slice(xs, attrs)
    elif op == "Fill":
        out = jnp.full(tuple(int(d) for d in _static(xs[0], "Fill dims")),
                       xs[1])
    else:
        raise NotImplementedError(f"TF op {op!r} (node {node['name']!r})")
    vals[node["name"]] = out
    vals[node["name"] + ":0"] = out


def _strided_slice(xs, attrs):
    x = xs[0]
    begin = _static(xs[1], "StridedSlice begin").astype(int)
    end = _static(xs[2], "StridedSlice end").astype(int)
    strides = (_static(xs[3], "StridedSlice strides").astype(int)
               if len(xs) > 3 else np.ones_like(begin))
    bm = attrs.get("begin_mask", 0)
    em = attrs.get("end_mask", 0)
    sm = attrs.get("shrink_axis_mask", 0)
    if attrs.get("new_axis_mask", 0) or attrs.get("ellipsis_mask", 0):
        raise NotImplementedError("StridedSlice new_axis/ellipsis masks")
    idx = []
    for i in range(len(begin)):
        if sm & (1 << i):
            idx.append(int(begin[i]))
            continue
        b = None if bm & (1 << i) else int(begin[i])
        e = None if em & (1 << i) else int(end[i])
        idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


# ---------------------------------------------------------------------------
# the Layer
# ---------------------------------------------------------------------------

class TFNet(Layer):
    """A frozen TF graph as a Layer.

    Float Const tensors of rank >= 1 become trainable params (pass
    ``trainable=False`` to pin them as host constants, matching the frozen
    semantics of the reference's TFNet); everything else (shapes, axes,
    perms, scalars) stays a host constant so structural ops see static
    values under jit.
    """

    def __init__(self, nodes: List[Dict[str, Any]],
                 inputs: Optional[List[str]] = None,
                 outputs: Optional[List[str]] = None,
                 trainable: bool = True, **kwargs):
        super().__init__(**kwargs)
        known = {n["name"] for n in nodes}
        for node in nodes:
            for raw in node["inputs"]:
                base, _, port = raw.lstrip("^").partition(":")
                if base not in known:
                    raise ValueError(
                        f"node {node['name']!r} consumes unknown tensor "
                        f"{raw!r}")
                if port not in ("", "0"):
                    raise NotImplementedError(
                        f"node {node['name']!r} consumes secondary output "
                        f"{raw!r}; only :0 outputs are computed")
        self.nodes = [n for n in nodes if n["op"] not in ("NoOp",)]
        placeholders = [n["name"] for n in self.nodes
                        if n["op"] == "Placeholder"]
        # PlaceholderWithDefault: only a feed when explicitly requested;
        # otherwise its input (the graph-supplied default) binds it at call.
        # A graph with NO pure Placeholder still needs somewhere to put the
        # caller's data — then the with-default nodes become the feeds.
        self._defaults = {n["name"]: n["inputs"][0].split(":")[0]
                          for n in self.nodes
                          if n["op"] == "PlaceholderWithDefault"
                          and n["inputs"]}
        self.feed_names = inputs or placeholders or list(self._defaults)
        if outputs:
            self.output_names = outputs
        else:
            consumed = set()
            for n in self.nodes:
                consumed.update(i.lstrip("^").split(":")[0]
                                for i in n["inputs"])
            self.output_names = [n["name"] for n in self.nodes
                                 if n["name"] not in consumed
                                 and n["op"] != "Const"] or \
                [self.nodes[-1]["name"]]

        structural = set()
        for n in self.nodes:
            names = [i for i in n["inputs"] if not i.startswith("^")]
            for pos, raw in enumerate(names):
                key = (n["op"], pos)
                last = (n["op"], -1)
                if (key in _STRUCTURAL or key in _FROZEN_STATS
                        or (last in _STRUCTURAL
                            and pos == len(names) - 1)):
                    structural.add(raw.split(":")[0])

        self.consts: Dict[str, np.ndarray] = {}
        weights: Dict[str, np.ndarray] = {}
        for n in self.nodes:
            if n["op"] != "Const":
                continue
            arr = n["attrs"].get("value")
            if arr is None:
                raise ValueError(f"Const node {n['name']!r} has no value")
            arr = np.asarray(arr)
            if (trainable and arr.ndim >= 1 and n["name"] not in structural
                    and np.issubdtype(arr.dtype, np.floating)):
                weights[n["name"]] = arr
            else:
                self.consts[n["name"]] = arr
        self._weights: Optional[Dict[str, np.ndarray]] = weights
        self._built_params: Optional[Dict[str, Any]] = None
        exec_nodes = [n for n in self.nodes
                      if n["op"] not in ("Const", "Placeholder",
                                         "PlaceholderWithDefault")]
        self._exec_nodes = self._topo_sort(exec_nodes)
        # fail at load, not mid-trace: dry-check op coverage against the
        # SAME set _run_node dispatches on (no second hand-kept list)
        for n in self._exec_nodes:
            if n["op"] not in _SUPPORTED_OPS:
                raise NotImplementedError(
                    f"TF op {n['op']!r} (node {n['name']!r})")

    @staticmethod
    def _topo_sort(nodes):
        """GraphDef does NOT guarantee topological node order (ONNX does);
        Kahn-sort (O(N+E), indegree counters + a by-file-order heap) so
        call() never reads a value before its producer ran, with
        deterministic ordering among ready nodes."""
        import heapq

        index = {n["name"]: i for i, n in enumerate(nodes)}
        indeg = {n["name"]: 0 for n in nodes}
        consumers: Dict[str, List[str]] = {n["name"]: [] for n in nodes}
        for n in nodes:
            deps = {raw.lstrip("^").split(":")[0] for raw in n["inputs"]}
            for d in deps:
                if d in indeg:
                    indeg[n["name"]] += 1
                    consumers[d].append(n["name"])
        ready = [index[name] for name, d in indeg.items() if d == 0]
        heapq.heapify(ready)
        ordered = []
        while ready:
            n = nodes[heapq.heappop(ready)]
            ordered.append(n)
            for c in consumers[n["name"]]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    heapq.heappush(ready, index[c])
        if len(ordered) != len(nodes):
            cyc = sorted(name for name, d in indeg.items() if d > 0)[:5]
            raise ValueError(f"GraphDef has a dependency cycle near {cyc}")
        return ordered

    def build(self, rng, input_shape=None):
        if self._built_params is None:
            self._built_params = {n: jnp.asarray(a)
                                  for n, a in self._weights.items()}
            self._weights = None
        return self._built_params

    def call(self, params, x, *, training=False, rng=None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        if len(xs) != len(self.feed_names):
            raise ValueError(f"expected {len(self.feed_names)} inputs "
                             f"({self.feed_names}), got {len(xs)}")
        vals: Dict[str, Any] = {}
        for name, arr in self.consts.items():
            vals[name] = arr
            vals[name + ":0"] = arr
        for name, arr in params.items():
            vals[name] = arr
            vals[name + ":0"] = arr
        for name, arr in zip(self.feed_names, xs):
            vals[name] = arr
            vals[name + ":0"] = arr
        for name, src in self._defaults.items():
            if name in vals:
                continue  # explicitly fed
            if src not in vals:
                raise ValueError(
                    f"PlaceholderWithDefault {name!r}: default {src!r} is "
                    f"not a constant; feed it explicitly via inputs=[...]")
            vals[name] = vals[src]
            vals[name + ":0"] = vals[src]
        for node in self._exec_nodes:
            _run_node(node, vals)
        outs = [vals[n] for n in self.output_names]
        return outs[0] if len(outs) == 1 else outs


def load_tf(path: str, inputs: Optional[List[str]] = None,
            outputs: Optional[List[str]] = None,
            trainable: bool = True) -> TFNet:
    """Load a frozen GraphDef ``.pb`` — ``Net.loadTF`` /
    ``TFNet(path)`` parity (``pipeline/api/Net.scala:123-171``)."""
    with open(path, "rb") as f:
        nodes = _decode_graph(f.read())
    if not nodes:
        raise ValueError(f"{path}: no nodes decoded — not a GraphDef?")
    return TFNet(nodes, inputs=inputs, outputs=outputs, trainable=trainable)
