"""TF SavedModel import — load a SavedModel directory (graph + variables)
as a fine-tunable native ``TFNet``.

Reference parity: ``TFNetForInference.scala:412``-scope loads SavedModels
*with variables intact* through a TF session and freezes them for
inference; the Python side is ``zoo.pipeline.api.net.TFNet.from_saved_model``.
Here there is no TF runtime: ``saved_model.pb`` (SavedModel → MetaGraphDef
→ GraphDef + SignatureDefs) is parsed with the in-repo wire codec, the
``variables/`` tensor bundle is read with ``utils/tensor_bundle.py``, and
each restored variable becomes a Const in the graph handed to ``TFNet`` —
where rank≥1 float values turn into TRAINABLE params, so an imported
SavedModel doesn't just serve, it fine-tunes (the capability the
reference's frozen session path never had).

Supported: TF1-style flat graphs (``tf.compat.v1`` Session export,
``simple_save``/``SavedModelBuilder``) with ref (``VariableV2``) or
resource (``VarHandleOp``/``ReadVariableOp``) variables. TF2
function-based SavedModels (compute hidden in FunctionDef libraries) are
rejected with a clear error — freeze those to a GraphDef first.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...utils.proto import parse_fields
from ...utils.tensor_bundle import read_tensor_bundle
from .tfnet import TFNet, _decode_graph

__all__ = ["load_saved_model"]

_VAR_OPS = ("VariableV2", "Variable", "VarHandleOp")


def _decode_string(payload) -> str:
    return payload.decode("utf-8") if isinstance(payload, (bytes, bytearray)) \
        else str(payload)


def _parse_tensor_info(payload: bytes) -> str:
    """TensorInfo → tensor name ("x:0")."""
    name = ""
    for f, wt, p in parse_fields(payload):
        if f == 1:
            name = _decode_string(p)
    return name


def _parse_signature(payload: bytes) -> Dict[str, Dict[str, str]]:
    sig = {"inputs": {}, "outputs": {}, "method": ""}
    for f, wt, p in parse_fields(payload):
        if f in (1, 2) and isinstance(p, (bytes, bytearray)):
            key, name = "", ""
            for ff, _, pp in parse_fields(p):
                if ff == 1:
                    key = _decode_string(pp)
                elif ff == 2:
                    name = _parse_tensor_info(pp)
            sig["inputs" if f == 1 else "outputs"][key] = name
        elif f == 3:
            sig["method"] = _decode_string(p)
    return sig


def _parse_meta_graph(payload: bytes):
    tags: List[str] = []
    graph_def: Optional[bytes] = None
    signatures: Dict[str, Dict] = {}
    has_functions = False
    for f, wt, p in parse_fields(payload):
        if f == 1 and isinstance(p, (bytes, bytearray)):  # MetaInfoDef
            for ff, _, pp in parse_fields(p):
                if ff == 4:
                    tags.append(_decode_string(pp))
        elif f == 2 and isinstance(p, (bytes, bytearray)):
            graph_def = bytes(p)
            for ff, _, pp in parse_fields(p):
                if ff == 2 and isinstance(pp, (bytes, bytearray)) and pp:
                    # GraphDef.library (FunctionDefLibrary) with content
                    for fff, _, _ppp in parse_fields(pp):
                        if fff == 1:  # at least one FunctionDef
                            has_functions = True
        elif f == 5 and isinstance(p, (bytes, bytearray)):  # signature map
            key, val = "", None
            for ff, _, pp in parse_fields(p):
                if ff == 1:
                    key = _decode_string(pp)
                elif ff == 2:
                    val = _parse_signature(pp)
            if val is not None:
                signatures[key] = val
    return tags, graph_def, signatures, has_functions


def _base(tensor_name: str) -> str:
    return tensor_name.split(":")[0]


def load_saved_model(path: str, signature: str = "serving_default",
                     tags: Optional[List[str]] = None,
                     inputs: Optional[List[str]] = None,
                     outputs: Optional[List[str]] = None,
                     trainable: bool = True) -> TFNet:
    """Load ``path/saved_model.pb`` + ``path/variables/`` as a ``TFNet``.

    ``signature`` picks the SignatureDef naming the input/output tensors
    (override with explicit ``inputs``/``outputs`` node names); ``tags``
    picks among multiple MetaGraphs (default: the first, which is the only
    one ``simple_save``-style exports carry). Feed order follows the
    signature's sorted input keys.
    """
    pb = os.path.join(path, "saved_model.pb")
    if not os.path.exists(pb):
        raise FileNotFoundError(f"{pb} not found — not a SavedModel dir?")
    with open(pb, "rb") as f:
        raw = f.read()

    metas = []
    for f_, wt, p in parse_fields(raw):
        if f_ == 2 and isinstance(p, (bytes, bytearray)):
            metas.append(_parse_meta_graph(bytes(p)))
    if not metas:
        raise ValueError(f"{pb}: no MetaGraphDef found")
    chosen = None
    if tags:
        for m in metas:
            if set(tags) <= set(m[0]):
                chosen = m
                break
        if chosen is None:
            raise ValueError(f"no MetaGraph tagged {tags}; available: "
                             f"{[m[0] for m in metas]}")
    else:
        chosen = metas[0]
    meta_tags, graph_bytes, signatures, has_functions = chosen
    if graph_bytes is None:
        raise ValueError(f"{pb}: MetaGraph has no GraphDef")

    nodes = _decode_graph(graph_bytes)
    if has_functions and not any(n["op"] in _VAR_OPS or n["op"] == "MatMul"
                                 for n in nodes):
        raise NotImplementedError(
            "TF2 function-based SavedModel (compute lives in FunctionDefs, "
            "main graph is empty) — export a TF1-style flat graph "
            "(tf.compat.v1 Session + simple_save) or freeze to a GraphDef")

    sig_inputs = sig_outputs = None
    if signatures:
        if signature not in signatures and (inputs is None or outputs is None):
            raise ValueError(f"signature {signature!r} not found; available: "
                             f"{sorted(signatures)}")
        if signature in signatures:
            sig = signatures[signature]
            sig_inputs = [_base(sig["inputs"][k])
                          for k in sorted(sig["inputs"])]
            sig_outputs = [_base(sig["outputs"][k])
                           for k in sorted(sig["outputs"])]
    feed = inputs or sig_inputs
    outs = outputs or sig_outputs
    if not feed or not outs:
        raise ValueError("SavedModel carries no usable signature; pass "
                         "inputs=[...] and outputs=[...] explicitly")

    # restore variables and substitute them as Consts
    bundle_prefix = os.path.join(path, "variables", "variables")
    variables: Dict[str, np.ndarray] = {}
    if os.path.exists(bundle_prefix + ".index"):
        variables = read_tensor_bundle(bundle_prefix)

    by_name = {n["name"]: n for n in nodes}
    new_nodes = []
    for n in nodes:
        if n["op"] in _VAR_OPS:
            key = n["attrs"].get("shared_name") or n["name"]
            if isinstance(key, (bytes, bytearray)):
                key = key.decode("utf-8")
            if key not in variables and n["name"] in variables:
                key = n["name"]
            if key not in variables:
                raise ValueError(
                    f"variable node {n['name']!r} has no value in the "
                    f"bundle (keys: {sorted(variables)[:8]}...)")
            new_nodes.append({"name": n["name"], "op": "Const",
                              "inputs": [],
                              "attrs": {"value": variables[key]}})
        else:
            new_nodes.append(n)
    by_name = {n["name"]: n for n in new_nodes}

    # reachable slice from the outputs: drops Saver/Assign/init machinery
    # (whose ops the executor rightly refuses)
    keep = set()
    stack = [_base(o) for o in outs] + [_base(i) for i in feed]
    while stack:
        name = stack.pop()
        if name in keep or name not in by_name:
            continue
        keep.add(name)
        for raw_in in by_name[name]["inputs"]:
            if raw_in.startswith("^"):
                continue  # control deps don't pull Saver/init machinery in
            stack.append(_base(raw_in))
    sliced = [n for n in new_nodes if n["name"] in keep]
    # control-dep pruning: inputs starting with ^ may point outside the
    # slice (e.g. ^init) — drop those edges
    for n in sliced:
        n["inputs"] = [i for i in n["inputs"]
                       if not i.startswith("^") or i[1:] in keep]

    net = TFNet(sliced, inputs=feed, outputs=outs, trainable=trainable)
    net.signature = signatures.get(signature)
    return net
