from .io import (NativeArrayFile, native_io_available,  # noqa: F401
                 load_native_io)
