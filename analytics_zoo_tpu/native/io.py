"""ctypes binding for the native IO library (``native/zoo_io.cc``) — the
host-side C++ component of the disk data tier (the reference's equivalent
layer is JNI: ``PersistentMemoryAllocator.java:37-43`` + BigDL's DISK_ONLY
persistence under ``FeatureSet.scala:332-409``).

The library is compiled on first use with the in-image ``g++`` (no
pybind11 — plain C ABI via ctypes) and cached next to the source. When no
compiler is available, :class:`NativeArrayFile` transparently falls back to
``numpy.memmap`` — same results, minus the native gather speed and the
background page prefetch.

File format: standard ``.npy`` (v1/v2). The Python side parses the header
(dtype, shape, data offset); the native side only ever sees flat bytes.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("analytics_zoo_tpu.native")

_lib = None
_lib_lock = threading.Lock()


def _configure(lib):
    lib.zoo_open.restype = ctypes.c_void_p
    lib.zoo_open.argtypes = [ctypes.c_char_p]
    lib.zoo_size.restype = ctypes.c_long
    lib.zoo_size.argtypes = [ctypes.c_void_p]
    lib.zoo_data.restype = ctypes.c_void_p
    lib.zoo_data.argtypes = [ctypes.c_void_p]
    lib.zoo_gather.restype = ctypes.c_int
    lib.zoo_gather.argtypes = [ctypes.c_void_p, ctypes.c_long, ctypes.c_long,
                               ctypes.POINTER(ctypes.c_long), ctypes.c_long,
                               ctypes.c_void_p]
    lib.zoo_prefetch.restype = ctypes.c_int
    lib.zoo_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_long,
                                 ctypes.c_long]
    lib.zoo_prefetch_wait.restype = None
    lib.zoo_prefetch_wait.argtypes = [ctypes.c_void_p]
    lib.zoo_close.restype = None
    lib.zoo_close.argtypes = [ctypes.c_void_p]
    return lib


def load_native_io() -> Optional[ctypes.CDLL]:
    """Load (building if needed) libzoo_io.so; None when unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        from analytics_zoo_tpu.native._loader import build_and_load
        lib = build_and_load("libzoo_io.so", "zoo_io.cc")
        try:
            _lib = _configure(lib) if lib is not None else False
        except AttributeError as e:   # stale/mismatched binary
            log.warning("native IO unavailable (%s); numpy.memmap fallback "
                        "in use", e)
            _lib = False
        return _lib or None


def native_io_available() -> bool:
    return load_native_io() is not None


def _read_npy_header(path: str) -> Tuple[np.dtype, Tuple[int, ...], int]:
    """(dtype, shape, data_offset) of a .npy file, C-order required."""
    with open(path, "rb") as f:
        version = np.lib.format.read_magic(f)
        shape, fortran, dtype = np.lib.format._read_array_header(f, version)
        if fortran:
            raise ValueError(f"{path}: Fortran-order arrays not supported")
        return np.dtype(dtype), tuple(shape), f.tell()


class NativeArrayFile:
    """Random-access reader over one ``.npy`` file: ``gather(indices)``
    copies the selected records into fresh DRAM; ``prefetch(lo, hi)``
    streams a record range's pages in the background."""

    def __init__(self, path: str):
        self.path = path
        self.dtype, self.shape, self.offset = _read_npy_header(path)
        if not self.shape:
            raise ValueError(f"{path}: scalar arrays have no records")
        self.n = int(self.shape[0])
        self.record_shape = tuple(self.shape[1:])
        self.record_bytes = int(np.prod(self.record_shape, dtype=np.int64)
                                * self.dtype.itemsize) or self.dtype.itemsize
        self._lib = load_native_io()
        if self._lib is not None:
            self._h = self._lib.zoo_open(path.encode())
            if not self._h:
                raise OSError(f"zoo_open failed for {path}")
            expected = self.offset + self.n * self.record_bytes
            if self._lib.zoo_size(self._h) < expected:
                self._lib.zoo_close(self._h)
                raise ValueError(f"{path}: file shorter than header claims")
        else:
            self._h = None
            self._mm = np.memmap(path, dtype=self.dtype, mode="r",
                                 offset=self.offset, shape=self.shape)

    def gather(self, indices: np.ndarray) -> np.ndarray:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        out = np.empty((len(idx),) + self.record_shape, self.dtype)
        if self._h is not None:
            rc = self._lib.zoo_gather(
                self._h, self.offset, self.record_bytes,
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), len(idx),
                out.ctypes.data_as(ctypes.c_void_p))
            if rc != 0:
                raise IndexError(f"{self.path}: gather index out of range")
            return out
        if len(idx) and (idx.min() < 0 or idx.max() >= self.n):
            raise IndexError(f"{self.path}: gather index out of range")
        out[...] = self._mm[idx]
        return out

    def prefetch(self, lo: int, hi: int) -> None:
        """Async page-in of records [lo, hi); no-op on the numpy fallback."""
        if self._h is None:
            return
        lo = max(int(lo), 0)
        hi = min(int(hi), self.n)
        if hi <= lo:
            return
        self._lib.zoo_prefetch(self._h, self.offset + lo * self.record_bytes,
                               (hi - lo) * self.record_bytes)

    def prefetch_wait(self) -> None:
        if self._h is not None:
            self._lib.zoo_prefetch_wait(self._h)

    def close(self) -> None:
        if getattr(self, "_h", None) is not None:
            self._lib.zoo_close(self._h)
            self._h = None
        if hasattr(self, "_mm"):
            del self._mm

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        # raising from __del__ aborts interpreter shutdown mid-GC — silence
        # is the contract here
        except Exception:  # noqa: BLE001  # zoolint: disable=ZL007
            pass
