"""ctypes binding for the native image-ops library (``native/zoo_image.cc``)
— the host-side C++ component of the image pipeline (the reference's
equivalent layer is OpenCV through BigDL's JNI:
``feature/image/OpenCVMethod.scala``, per-transformer use in
``feature/image/*.scala``).

Two batched ops back the hot transformers:

* :func:`resize_bilinear` — separable triangle-filter resampling, threaded
  over the batch (replaces a per-image Python/PIL loop);
* :func:`normalize` — fused dtype-convert + per-channel ``(x - mean) / std``
  in one pass.

Compiled on first use with the in-image ``g++`` (plain C ABI — no pybind11)
and cached next to the source; when no compiler is available every caller
falls back to its numpy/PIL path — same results, minus the speed.
"""

from __future__ import annotations

import ctypes
import logging
import threading
from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.native._loader import build_and_load

log = logging.getLogger("analytics_zoo_tpu.native")

_lib = None
_lib_lock = threading.Lock()


def _configure(lib):
    lib.zoo_image_resize.restype = ctypes.c_int
    lib.zoo_image_resize.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_long, ctypes.c_long,
        ctypes.c_long, ctypes.c_long, ctypes.c_void_p, ctypes.c_long,
        ctypes.c_long, ctypes.c_int]
    lib.zoo_image_normalize.restype = ctypes.c_int
    lib.zoo_image_normalize.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_long, ctypes.c_long,
        ctypes.c_long, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int]
    return lib


def load_native_image() -> Optional[ctypes.CDLL]:
    """Load (building if needed) libzoo_image.so; None when unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        lib = build_and_load("libzoo_image.so", "zoo_image.cc")
        try:
            _lib = _configure(lib) if lib is not None else False
        except AttributeError as e:   # stale/mismatched binary
            log.info("native image ops unavailable (%s); using numpy/PIL "
                     "fallbacks", e)
            _lib = False
        return _lib or None


def available() -> bool:
    return load_native_image() is not None


def _as_batch(arr: np.ndarray):
    """(H, W, C)/(N, H, W, C) -> contiguous (N, H, W, C) + had_batch flag."""
    if arr.ndim == 3:
        return np.ascontiguousarray(arr[None]), False
    if arr.ndim == 4:
        return np.ascontiguousarray(arr), True
    raise ValueError(f"expected (H, W, C) or (N, H, W, C), got {arr.shape}")


def resize_bilinear(arr: np.ndarray, out_h: int, out_w: int,
                    nthreads: int = 0) -> Optional[np.ndarray]:
    """Batched triangle-filter resize; None when the native lib or dtype
    path is unavailable (caller falls back to PIL)."""
    lib = load_native_image()
    if lib is None:
        return None
    if arr.dtype == np.uint8:
        is_f32 = 0
    elif arr.dtype == np.float32:
        is_f32 = 1
    else:
        return None
    batch, had_batch = _as_batch(arr)
    n, h, w, c = batch.shape
    out = np.empty((n, int(out_h), int(out_w), c), batch.dtype)
    rc = lib.zoo_image_resize(
        batch.ctypes.data_as(ctypes.c_void_p), is_f32, n, h, w, c,
        out.ctypes.data_as(ctypes.c_void_p), int(out_h), int(out_w),
        int(nthreads))
    if rc != 0:
        return None
    return out if had_batch else out[0]


def normalize(arr: np.ndarray, mean: Sequence[float], std: Sequence[float],
              nthreads: int = 0) -> Optional[np.ndarray]:
    """Fused convert + per-channel normalize to float32; None when
    unavailable (caller falls back to numpy)."""
    lib = load_native_image()
    if lib is None:
        return None
    if arr.dtype == np.uint8:
        is_f32 = 0
    elif arr.dtype == np.float32:
        is_f32 = 1
    else:
        return None
    batch, had_batch = _as_batch(arr)
    n, h, w, c = batch.shape
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    if mean.shape != (c,) or std.shape != (c,) or np.any(std == 0):
        return None
    inv = np.ascontiguousarray(1.0 / std, np.float32)
    out = np.empty(batch.shape, np.float32)
    fptr = ctypes.POINTER(ctypes.c_float)
    rc = lib.zoo_image_normalize(
        batch.ctypes.data_as(ctypes.c_void_p), is_f32, n, h * w, c,
        mean.ctypes.data_as(fptr), inv.ctypes.data_as(fptr),
        out.ctypes.data_as(fptr), int(nthreads))
    if rc != 0:
        return None
    return out if had_batch else out[0]
