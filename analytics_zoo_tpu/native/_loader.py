"""Shared loader for the host-side C++ libraries under ``native/``
(``zoo_io.cc``, ``zoo_image.cc``). One place owns the build rule so the
compiler flags can't drift between libraries (they mirror
``native/Makefile``), and first-use builds are concurrency-safe: the
compile targets a pid-unique temp path and ``os.replace``s into place, so
two processes racing the same missing ``.so`` can never leave a corrupt
half-written library behind (a corrupt file would otherwise look newer
than its source and suppress every future rebuild)."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

log = logging.getLogger("analytics_zoo_tpu.native")

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

# keep in sync with native/Makefile
CXXFLAGS = ["-O2", "-std=c++17", "-fPIC", "-Wall"]
LDFLAGS = ["-shared", "-pthread"]


def build_and_load(so_name: str, src_name: str) -> Optional[ctypes.CDLL]:
    """dlopen ``native/<so_name>``, building it from ``native/<src_name>``
    first when missing or older than the source. Returns None on any
    failure (callers fall back to their pure-Python paths)."""
    so = os.path.join(NATIVE_DIR, so_name)
    src = os.path.join(NATIVE_DIR, src_name)
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            tmp = f"{so}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", *CXXFLAGS, src, *LDFLAGS, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)   # atomic: winners fully overwrite
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            log.info("built native library %s", so)
        return ctypes.CDLL(so)
    except Exception as e:  # noqa: BLE001 — any failure → Python fallback
        log.info("native library %s unavailable (%s)", so_name, e)
        return None
