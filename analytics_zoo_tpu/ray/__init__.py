from .raycontext import (ActorHandle, ObjectRef, RayContext,  # noqa: F401
                         RayTaskError)
