"""L10 — Ray-equivalent task runtime (parity with RayOnSpark,
``pyzoo/zoo/ray/util/raycontext.py``: ``RayContext(sc).init()`` boots ray
workers next to the data; ``JVMGuard``/``ProcessMonitor``
(``ray/util/process.py``) kill them when the driver dies).

TPU-native redesign: the reference needs a second scheduler because Spark
executors can't host arbitrary stateful actors; a TPU-VM host is just a
Linux box, so the runtime is a process pool on the host — stateless
``remote`` tasks round-trip through a shared queue, stateful actors get a
dedicated process. Worker processes are daemonic and additionally
self-terminate when the parent pid disappears (the JVMGuard role).
Multi-host placement is deliberately NOT re-invented here: under
``jax.distributed`` every host already runs the same program, so "run an
actor on each host" is the program itself.
"""

from __future__ import annotations

import atexit
import itertools
import multiprocessing as mp
import os
import pickle
import queue as queue_mod
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

__all__ = ["RayContext", "ObjectRef", "ActorHandle", "RayTaskError"]


class RayTaskError(RuntimeError):
    """A task raised; carries the worker-side traceback."""


def _mp_context():
    """forkserver first: the driver is a JAX process (multi-threaded, device
    handles open) — plain fork of it risks deadlocks in children. Payloads
    must therefore be picklable, same as ray's own contract."""
    for method in ("forkserver", "fork", "spawn"):
        if method in mp.get_all_start_methods():
            return mp.get_context(method)
    return mp.get_context()


class ObjectRef:
    """Future handle (the ``ray.ObjectRef`` role)."""

    __slots__ = ("id",)

    def __init__(self, id_: int):
        self.id = id_

    def __repr__(self):
        return f"ObjectRef({self.id})"


def _parent_guard(parent_pid: int, poll_s: float = 1.0):
    """Worker-side thread: exit hard if the parent process disappears
    (ProcessMonitor/JVMGuard parity — orphaned workers must not linger)."""

    def watch():
        while True:
            try:
                os.kill(parent_pid, 0)
            except OSError:
                os._exit(1)
            time.sleep(poll_s)

    threading.Thread(target=watch, daemon=True).start()


def _put_result(result_q: mp.Queue, task_id: int, fn_call):
    """Run and reply; unpicklable RESULTS must become errors here — the
    queue's feeder thread would otherwise drop them silently and the
    driver's get() would hang."""
    try:
        result = fn_call()
        pickle.dumps(result)
        result_q.put((task_id, True, result))
    except BaseException:  # noqa: BLE001 — workers must not die on task errors
        result_q.put((task_id, False, traceback.format_exc()))


def _pool_worker(parent_pid: int, task_q: mp.Queue, result_q: mp.Queue):
    _parent_guard(parent_pid)
    while True:
        item = task_q.get()
        if item is None:
            return
        task_id, fn, args, kwargs = item
        _put_result(result_q, task_id, lambda: fn(*args, **kwargs))


def _actor_worker(parent_pid: int, cls, init_args, init_kwargs,
                  cmd_q: mp.Queue, result_q: mp.Queue, ack_id: int):
    _parent_guard(parent_pid)
    try:
        obj = cls(*init_args, **init_kwargs)
    except BaseException:
        result_q.put((ack_id, False, traceback.format_exc()))
        return
    result_q.put((ack_id, True, None))  # construction ack
    while True:
        item = cmd_q.get()
        if item is None:
            return
        task_id, method, args, kwargs = item
        _put_result(result_q, task_id,
                    lambda: getattr(obj, method)(*args, **kwargs))


class _ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._handle._call(self._name, args, kwargs)


class ActorHandle:
    """``actor.method.remote(...)`` → ObjectRef (the ray actor surface)."""

    def __init__(self, ctx: "RayContext", cmd_q: mp.Queue,
                 proc: mp.Process):
        self._ctx = ctx
        self._cmd_q = cmd_q
        self._proc = proc

    def _call(self, method: str, args, kwargs) -> ObjectRef:
        RayContext._check_picklable((args, kwargs), f"{method}() arguments")
        ref = ObjectRef(next(self._ctx._ids))
        self._cmd_q.put((ref.id, method, args, kwargs))
        return ref

    def __getattr__(self, name: str) -> _ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _ActorMethod(self, name)

    def terminate(self):
        self._cmd_q.put(None)
        self._proc.join(timeout=5)
        if self._proc.is_alive():
            self._proc.terminate()
        if self in self._ctx._actors:  # deliberate exit ≠ crashed worker
            self._ctx._actors.remove(self)


class RayContext:
    """``RayContext(num_workers).init()`` → ``remote``/``get``/``actor``.

    The surface mirrors the RayOnSpark bring-up (``raycontext.py:192``):
    ``init`` boots the workers, ``stop`` tears everything down, and workers
    cannot outlive the driver.
    """

    def __init__(self, num_workers: Optional[int] = None):
        self.num_workers = int(num_workers or (os.cpu_count() or 2))
        self._ids = itertools.count()
        self._mp_ctx = _mp_context()
        self._procs: List[mp.Process] = []
        self._actors: List[ActorHandle] = []
        self._task_q: Optional[mp.Queue] = None
        self._result_q: Optional[mp.Queue] = None
        self._results: Dict[int, Any] = {}
        self._initialized = False

    # ------------------------------------------------------------------
    def init(self) -> "RayContext":
        if self._initialized:
            return self
        ctx = self._mp_ctx
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        for _ in range(self.num_workers):
            p = ctx.Process(target=_pool_worker,
                            args=(os.getpid(), self._task_q, self._result_q),
                            daemon=True)
            p.start()
            self._procs.append(p)
        self._initialized = True
        atexit.register(self.stop)
        return self

    def _require_init(self):
        if not self._initialized:
            raise RuntimeError("RayContext not initialized — call init()")

    # ------------------------------------------------------------------
    @staticmethod
    def _check_picklable(payload, what: str):
        """Surface pickling failures at submission — mp.Queue serializes in
        a background feeder thread where they would vanish and hang get()."""
        try:
            pickle.dumps(payload)
        except Exception as e:
            raise RayTaskError(f"{what} is not picklable (payloads cross "
                               f"process boundaries by pickle): {e}") from e

    def remote(self, fn: Callable, *args, **kwargs) -> ObjectRef:
        """Submit ``fn(*args, **kwargs)`` to the worker pool."""
        self._require_init()
        self._check_picklable((fn, args, kwargs), "task")
        ref = ObjectRef(next(self._ids))
        self._task_q.put((ref.id, fn, args, kwargs))
        return ref

    def actor(self, cls, *args, **kwargs) -> ActorHandle:
        """Start a dedicated stateful worker running ``cls(*args)``."""
        self._require_init()
        self._check_picklable((cls, args, kwargs), "actor spec")
        ctx = self._mp_ctx
        cmd_q = ctx.Queue()
        # construction ack uses a UNIQUE id from the shared counter — a
        # fixed sentinel would hit the first actor's cached ack and mask a
        # later actor's failed __init__ (results are cached, never popped)
        ack_id = next(self._ids)
        p = ctx.Process(target=_actor_worker,
                        args=(os.getpid(), cls, args, kwargs, cmd_q,
                              self._result_q, ack_id),
                        daemon=True)
        p.start()
        # surface __init__ failures immediately; p is passed so a child
        # dying WITHOUT an ack (segfault, os._exit, unpicklable class in a
        # spawn context) raises instead of hanging the 0.2s poll forever
        try:
            ok, payload = self._wait_for(ack_id, extra_proc=p)
        except BaseException:
            p.join(timeout=1)  # reap — a no-ack death must not zombie
            raise
        if not ok:
            p.join(timeout=1)
            raise RayTaskError(f"actor construction failed:\n{payload}")
        h = ActorHandle(self, cmd_q, p)
        self._actors.append(h)
        return h

    # ------------------------------------------------------------------
    def _dead_workers(self) -> List[int]:
        return [p.pid for p in self._procs if not p.is_alive()] + \
            [h._proc.pid for h in self._actors if not h._proc.is_alive()]

    def _wait_for(self, task_id: int, deadline: Optional[float] = None,
                  extra_proc=None):
        # results are cached, not popped: get() on the same ref twice
        # returns the same value (ray.get semantics)
        extra_dead_at = None
        while task_id not in self._results:
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"ObjectRef({task_id}) not ready before "
                                   f"timeout")
            # liveness of the just-spawned (untracked) process is checked
            # EVERY iteration: a steady stream of unrelated pool results
            # would otherwise starve the Empty branch and re-open the hang
            if extra_proc is not None and not extra_proc.is_alive():
                # wall-clock grace (not iterations — a busy result queue
                # spins iterations in microseconds): the dead child's queue
                # feeder gets ~1s to flush a final (failure) ack
                now = time.monotonic()
                if extra_dead_at is None:
                    extra_dead_at = now
                elif now - extra_dead_at > 1.0:
                    raise RayTaskError(
                        f"actor process {extra_proc.pid} died before "
                        f"delivering its construction ack (segfault / "
                        f"os._exit in __init__?)")
            try:
                # bounded poll so crashed workers are detected even with no
                # deadline (a dead worker's result will never arrive)
                got_id, ok, payload = self._result_q.get(timeout=0.2)
                self._results[got_id] = (ok, payload)
            except queue_mod.Empty:
                dead = self._dead_workers()
                if dead:
                    raise RayTaskError(
                        f"worker process(es) {dead} died before delivering "
                        f"ObjectRef({task_id}) (crashed / OOM-killed?)")
        return self._results[task_id]

    def get(self, refs: Union[ObjectRef, Sequence[ObjectRef]],
            timeout: Optional[float] = None):
        """Block for result(s). Task errors raise :class:`RayTaskError`;
        expiry raises :class:`TimeoutError` (the timeout bounds the WHOLE
        call, also for a list of refs)."""
        self._require_init()
        deadline = None if timeout is None else time.monotonic() + timeout
        if isinstance(refs, ObjectRef):
            refs_list = [refs]
        else:
            refs_list = list(refs)
        out = []
        for r in refs_list:
            ok, payload = self._wait_for(r.id, deadline)
            if not ok:
                raise RayTaskError(f"task failed:\n{payload}")
            out.append(payload)
        return out[0] if isinstance(refs, ObjectRef) else out

    # ------------------------------------------------------------------
    def stop(self):
        if not self._initialized:
            return
        for h in self._actors:
            try:
                h.terminate()
            except Exception:  # noqa: BLE001 — best-effort teardown  # zoolint: disable=ZL007
                pass
        for _ in self._procs:
            try:
                self._task_q.put(None)
            except Exception:  # noqa: BLE001 — best-effort teardown  # zoolint: disable=ZL007
                pass
        for p in self._procs:
            p.join(timeout=2)
            if p.is_alive():
                p.terminate()
        self._procs.clear()
        self._actors.clear()
        self._initialized = False
