"""Contract surfaces — the code↔docs reconciliation half of the zoolint
project pass (``analysis/project.py``).

The ROADMAP's standing constraints make four runtime surfaces
*catalogued*: every metric family must have a row in
docs/guides/OBSERVABILITY.md, every ``zoo.*`` conf key a row in
docs/CONFIG.md (and a ``DEFAULT_CONF`` entry in ``common/context.py``),
every fault site a row in docs/guides/RELIABILITY.md, and every zoolint
rule a row in docs/guides/STATIC_ANALYSIS.md. After ten PRs those
surfaces hold ~60 metric families, ~40 conf keys and a dozen fault
sites — drift is a when-not-if bug class, and reviewer discipline does
not scale to it. This module makes the catalogs build-time-checked:

* **extractors** walk every module's AST and pull the call sites that
  *create* the surface — ``registry.counter/gauge/histogram/summary``
  registrations (constant, constant-folded and f-string names; literal
  label sets, including comprehension-bound label values), conf-key
  reads (``.get("zoo.x")`` / ``self._conf(...)`` / ``tri_state_conf``
  / ``conf["zoo.x"]`` subscripts), ``faults.inject("site")`` calls
  (import-resolved so only the real faults module counts), and zoolint
  rule declarations (``id = "ZLxxx"`` class attributes);
* **catalog parsers** read the first column of the relevant markdown
  table (OBSERVABILITY.md "Metric catalog", CONFIG.md key table,
  RELIABILITY.md fault-site table, STATIC_ANALYSIS.md rule table);
* **reconciliation rules** (ZL016–ZL020, registered on the project
  pass) report BOTH drift directions — code-not-documented anchors at
  the offending call site, documented-not-in-code anchors at the stale
  doc row.

Run via ``python -m analytics_zoo_tpu.analysis --contracts`` (exit 0
clean / 2 findings) or ``lint_project(...)`` in-process; the tier-1
gate (``tests/test_zoolint.py``) holds the live package + docs to zero.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import ERROR, Finding, ModuleContext, dotted, folded_str
from .project import ProjectContext, ProjectRule, register_project

METRIC_KINDS = ("counter", "gauge", "histogram", "summary")

#: a conf key string literal — the FULL string must look like one
#: (substrings inside prose/error messages never match)
_CONF_KEY_RE = re.compile(r"zoo(\.[a-z0-9_]+)+\Z")
#: a fault-site string: lowercase dotted pair(s), e.g. ``backend.xread``
_SITE_RE = re.compile(r"[a-z0-9_]+(\.[a-z0-9_]+)+\Z")
_RULE_ID_RE = re.compile(r"ZL\d{3}\Z")


# ---------------------------------------------------------------------------
# code-side extraction
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MetricSite:
    """One ``registry.<kind>(name, ...)`` registration call."""

    name: Optional[str]         # None = not statically resolvable
    exact: bool                 # False = f-string holes folded to `*`
    kind: str                   # counter | gauge | histogram | summary
    path: str
    line: int
    label_keys: Tuple[str, ...]
    #: label keys whose VALUE is not a constant and not bound by a loop
    #: over a literal collection — the unbounded-cardinality hazard
    dynamic_label_keys: Tuple[str, ...]
    #: labels= passed but not as a dict literal (opaque to the scan)
    opaque_labels: bool = False


def _is_registry_recv(node: ast.AST) -> bool:
    """Whether a call receiver looks like a MetricsRegistry — the
    ``default_registry()`` factory or a registry-named binding
    (``m``/``reg``/``registry``/``self.metrics``/``self._registry``).
    Purely lexical on purpose: the convention is enforced by ZL015, so a
    registry smuggled under a novel name shows up in review as "why is
    this not scanned", not as a silent hole."""
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        return bool(d) and d.split(".")[-1] == "default_registry"
    d = dotted(node)
    if not d:
        return False
    leaf = d.split(".")[-1].lower()
    return (leaf in ("m", "reg", "metrics")
            or leaf == "registry" or leaf.endswith("_registry")
            or leaf.endswith("_reg"))


def _local_const_str(ctx: ModuleContext,
                     at: ast.AST, name: str) -> Optional[Tuple[str, bool]]:
    """Fold a Name argument through the single constant assignment it
    refers to in an enclosing scope, if there is exactly one."""
    scope = ctx._enclosing_scope(at)
    while scope is not None:
        found: List[Tuple[str, bool]] = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        f = folded_str(node.value)
                        if f is not None:
                            found.append(f)
        if found:
            texts = {f[0] for f in found}
            if len(texts) == 1:
                return found[0]
            return None          # ambiguous rebinding: give up
        if isinstance(scope, ast.Module):
            return None
        scope = ctx._enclosing_scope(scope)
    return None


def _fold_arg(ctx: ModuleContext, call: ast.Call,
              node: ast.AST) -> Optional[Tuple[str, bool]]:
    f = folded_str(node)
    if f is not None:
        return f
    if isinstance(node, ast.Name):
        return _local_const_str(ctx, call, node.id)
    return None


def _local_dict(ctx: ModuleContext, at: ast.AST,
                name: str) -> Optional[ast.Dict]:
    """The single local ``name = {...}`` dict-literal binding visible
    from ``at``, if unambiguous (the ``labels = {...}; reg.gauge(...,
    labels=labels)`` idiom)."""
    scope = ctx._enclosing_scope(at)
    while scope is not None:
        found: List[ast.Dict] = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name \
                            and isinstance(node.value, ast.Dict):
                        found.append(node.value)
        if found:
            return found[0] if len(found) == 1 else None
        if isinstance(scope, ast.Module):
            return None
        scope = ctx._enclosing_scope(scope)
    return None


def _loop_bound_literals(ctx: ModuleContext, node: ast.AST) -> Set[str]:
    """Names bound, on ``node``'s parent chain, by a comprehension or
    ``for`` statement iterating a LITERAL tuple/list/set of constants —
    a label value fed from one is a bounded series set, not unbounded
    cardinality (the ``for reason in ("depth", "deadline")`` idiom)."""
    out: Set[str] = set()

    def literal_iter(it: ast.AST) -> bool:
        return (isinstance(it, (ast.Tuple, ast.List, ast.Set))
                and all(isinstance(e, ast.Constant) for e in it.elts))

    def add_target(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add_target(e)

    cur = node
    while cur is not None:
        if isinstance(cur, (ast.ListComp, ast.SetComp, ast.DictComp,
                            ast.GeneratorExp)):
            for gen in cur.generators:
                if literal_iter(gen.iter):
                    add_target(gen.target)
        elif isinstance(cur, (ast.For, ast.AsyncFor)) \
                and literal_iter(cur.iter):
            add_target(cur.target)
        cur = ctx.parent(cur)
    return out


def _helper_kind(func: ast.AST) -> Optional[str]:
    """The metric kind a ``*_<kind>`` helper-constructor name implies
    (``alert_gauge`` → ``gauge``, ``collector_counter`` → ``counter``),
    or None for anything else."""
    if isinstance(func, ast.Name):
        leaf = func.id
    elif isinstance(func, ast.Attribute):
        leaf = func.attr
    else:
        return None
    head, sep, tail = leaf.rpartition("_")
    return tail if sep and head and tail in METRIC_KINDS else None


def _helper_scan(ctx: ModuleContext) -> Tuple[Set[int], Set[str], Set[str]]:
    """Classify this module's ``*_<kind>`` helper definitions:
    ``(shim_call_ids, shim_helpers, local_helpers)``.

    A **forwarding shim** (``def alert_gauge(registry, name, ...):
    return registry.gauge(name, ...)``) registers whatever its CALLER
    names — so the inner call is excluded from the scan
    (``shim_call_ids``) and the helper's call sites become the
    registration sites. A ``*_<kind>``-named local function that is
    NOT a shim (it registers its own constant name, e.g. tracing's
    ``_span_histogram``) keeps its inner call as the site and its
    call sites stay out of the scan."""
    shim_calls: Set[int] = set()
    shim_helpers: Set[str] = set()
    local_helpers: Set[str] = set()
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        kind = _helper_kind(ast.Name(id=fn.name))
        if kind is None:
            continue
        local_helpers.add(fn.name)
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == kind
                    and _is_registry_recv(node.func.value)):
                continue
            name_node = node.args[0] if node.args else None
            if name_node is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        name_node = kw.value
            if isinstance(name_node, ast.Name) \
                    and name_node.id in params:
                shim_calls.add(id(node))
                shim_helpers.add(fn.name)
    return shim_calls, shim_helpers, local_helpers


def iter_metric_sites(ctx: ModuleContext) -> Iterator[MetricSite]:
    """Every metric registration call in one module — direct
    ``<registry>.<kind>(name, ...)`` attribute calls plus calls
    through ``*_<kind>`` helper constructors whose first argument is a
    registry (``alert_gauge(registry, name, ...)``); the forwarding
    shim inside such a helper is attributed to its callers (see
    :func:`_shim_call_ids`)."""
    shims, shim_helpers, local_helpers = _helper_scan(ctx)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or id(node) in shims:
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_KINDS
                and _is_registry_recv(node.func.value)):
            kind = node.func.attr
            pos_args = node.args
        else:
            kind = _helper_kind(node.func)
            if kind is None or not node.args \
                    or not _is_registry_recv(node.args[0]):
                continue
            leaf = node.func.id if isinstance(node.func, ast.Name) \
                else node.func.attr
            if leaf in local_helpers and leaf not in shim_helpers:
                # a self-registering wrapper (its inner call is the
                # site), not a forwarding shim
                continue
            pos_args = node.args[1:]
        name_node: Optional[ast.AST] = None
        if pos_args:
            name_node = pos_args[0]
        else:
            for kw in node.keywords:
                if kw.arg == "name":
                    name_node = kw.value
        if name_node is None:
            continue            # no name argument: not a registration
        folded = _fold_arg(ctx, node, name_node)
        keys: List[str] = []
        dynamic: List[str] = []
        opaque = False
        for kw in node.keywords:
            if kw.arg != "labels" or kw.value is None:
                continue
            label_dict = kw.value
            if isinstance(label_dict, ast.Name):
                # fold through a single local `labels = {...}` binding
                label_dict = _local_dict(ctx, node, label_dict.id)
            if not isinstance(label_dict, ast.Dict):
                opaque = True
                continue
            bounded = _loop_bound_literals(ctx, node)
            for k, v in zip(label_dict.keys, label_dict.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    key = k.value
                else:
                    key = "<dynamic>"
                keys.append(key)
                vf = folded_str(v)
                if vf is not None and vf[1]:
                    continue                        # constant value
                if isinstance(v, ast.Name) and v.id in bounded:
                    continue                        # literal-loop bound
                if isinstance(v, ast.JoinedStr):
                    # an f-string whose holes are all bounded loop names
                    holes = [h.value for h in v.values
                             if isinstance(h, ast.FormattedValue)]
                    if all(isinstance(h, ast.Name) and h.id in bounded
                           for h in holes):
                        continue
                dynamic.append(key)
        yield MetricSite(
            name=None if folded is None else folded[0],
            exact=folded is not None and folded[1],
            kind=kind, path=ctx.path, line=node.lineno,
            label_keys=tuple(keys), dynamic_label_keys=tuple(dynamic),
            opaque_labels=opaque)


@dataclasses.dataclass
class ConfRead:
    key: str
    path: str
    line: int


def _module_locals_named(ctx: ModuleContext, leaf: str) -> Set[str]:
    """Local names plausibly bound to a module whose dotted path ends in
    ``leaf`` (``import a.b.faults as f`` / ``from ..common import
    faults``)."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == leaf or a.name.endswith("." + leaf):
                    out.add(a.asname or a.name.split(".", 1)[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == leaf:
                    out.add(a.asname or a.name)
    return out


def _from_imported_of(ctx: ModuleContext, mod_leaf: str,
                      func: str) -> Set[str]:
    """Local names for ``from <...mod_leaf> import func [as alias]``."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == mod_leaf
                or node.module.endswith("." + mod_leaf)):
            for a in node.names:
                if a.name == func:
                    out.add(a.asname or a.name)
    return out


_CONF_CALL_ATTRS = ("get", "_conf")
#: bare helper names accepted as conf reads (`_conf("zoo.k", d)` — the
#: module-local wrapper idiom, cf. ops/fused_cross_entropy.py)
_CONF_CALL_NAMES = ("_conf", "conf_get", "get_conf", "tri_state_conf")


def iter_conf_reads(ctx: ModuleContext,
                    project=None) -> Iterator[ConfRead]:
    """``zoo.*`` conf-key reads: ``<x>.get("zoo.k", ...)`` /
    ``self._conf("zoo.k", ...)`` / bare ``_conf("zoo.k", ...)`` wrappers
    / ``tri_state_conf("zoo.k")`` calls and ``<x>["zoo.k"]`` subscript
    loads. Only FULL-string key literals count — a key mentioned inside
    an error message is prose, not a read. Under the project pass the
    symbol index resolves what ``tri_state_conf`` refers to (relative
    imports included); standalone use falls back to file-local
    from-import matching."""
    if project is not None:
        tri_state = {local for local, fq in project.imports(ctx).items()
                     if fq.split(".")[-1] == "tri_state_conf"}
    else:
        tri_state = _from_imported_of(ctx, "context", "tri_state_conf")
    tri_state.update(_CONF_CALL_NAMES)
    for node in ast.walk(ctx.tree):
        key_node: Optional[ast.AST] = None
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            leaf = d.split(".")[-1] if d else None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _CONF_CALL_ATTRS and node.args:
                key_node = node.args[0]
            elif leaf in tri_state and node.args:
                key_node = node.args[0]
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            key_node = node.slice
        if key_node is None:
            continue
        if isinstance(key_node, ast.Constant) \
                and isinstance(key_node.value, str) \
                and _CONF_KEY_RE.match(key_node.value):
            yield ConfRead(key_node.value, ctx.path, node.lineno)


@dataclasses.dataclass
class ConfDefault:
    key: str
    path: str
    line: int


_DEFAULTS_NAMES = ("DEFAULT_CONF", "_DEFAULTS")


def conf_defaults(ctx: ModuleContext) -> List[ConfDefault]:
    """Entries of a module-level ``DEFAULT_CONF = {...}`` (or
    ``_DEFAULTS = {...}``) dict literal — the bundled-defaults table the
    conf surface reconciles against."""
    out: List[ConfDefault] = []
    for stmt in ctx.tree.body:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if not any(isinstance(t, ast.Name) and t.id in _DEFAULTS_NAMES
                   for t in targets):
            continue
        if not isinstance(value, ast.Dict):
            continue
        for k in value.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and _CONF_KEY_RE.match(k.value):
                out.append(ConfDefault(k.value, ctx.path, k.lineno))
    return out


@dataclasses.dataclass
class FaultSite:
    site: Optional[str]
    exact: bool
    path: str
    line: int


def iter_fault_sites(ctx: ModuleContext,
                     project=None) -> Iterator[FaultSite]:
    """``faults.inject("site")`` call sites, import-resolved: the
    receiver must be a module named ``faults`` (any package prefix) or a
    bare ``inject`` from-imported off one — a foreign ``x.inject()`` is
    never mistaken for fault instrumentation. Under the project pass the
    package-wide symbol index is the authority (``from ..common import
    faults`` resolves through the module's own dotted path); standalone
    use falls back to file-local lexical matching."""
    if project is not None:
        faults_mods: Set[str] = set()
        bare_inject: Set[str] = set()
        for local, fq in project.imports(ctx).items():
            parts = fq.split(".")
            if parts[-1] == "faults":
                faults_mods.add(local)
            elif parts[-1] == "inject" and len(parts) >= 2 \
                    and parts[-2] == "faults":
                bare_inject.add(local)
    else:
        faults_mods = _module_locals_named(ctx, "faults")
        bare_inject = _from_imported_of(ctx, "faults", "inject")
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        d = dotted(node.func)
        if not d:
            continue
        hit = False
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            hit = leaf == "inject" and prefix in faults_mods
        else:
            hit = d in bare_inject
        if not hit:
            continue
        folded = _fold_arg(ctx, node, node.args[0])
        yield FaultSite(
            site=None if folded is None else folded[0],
            exact=folded is not None and folded[1],
            path=ctx.path, line=node.lineno)


@dataclasses.dataclass
class RuleDecl:
    rule_id: str
    severity: str       # "error" | "warning" | "" (unknown)
    path: str
    line: int


def iter_rule_decls(ctx: ModuleContext) -> Iterator[RuleDecl]:
    """zoolint rule declarations: a class body assigning ``id =
    "ZLxxx"`` (the registration decorator is not required — an
    unregistered rule class is exactly the drift worth catching)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        rule_id: Optional[Tuple[str, int]] = None
        severity = ""
        for stmt in node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            for t in stmt.targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id == "id" and isinstance(stmt.value, ast.Constant) \
                        and isinstance(stmt.value.value, str) \
                        and _RULE_ID_RE.match(stmt.value.value):
                    rule_id = (stmt.value.value, stmt.lineno)
                elif t.id == "severity":
                    sd = dotted(stmt.value)
                    if sd:
                        severity = sd.split(".")[-1].lower()
                    elif isinstance(stmt.value, ast.Constant):
                        severity = str(stmt.value.value).lower()
        if rule_id is not None:
            yield RuleDecl(rule_id[0], severity or "error",
                           ctx.path, rule_id[1])


# ---------------------------------------------------------------------------
# catalog (markdown) parsing
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DocEntry:
    value: str
    path: str
    line: int
    label_keys: Tuple[str, ...] = ()
    row: str = ""               # the remaining cells, for severity checks


_BACKTICK_RE = re.compile(r"`([^`]+)`")
_LABEL_KEY_RE = re.compile(r"([A-Za-z_][A-Za-z0-9_]*)=")


def md_table_column(text: str, path: str,
                    header: str) -> List[Tuple[str, int, str]]:
    """``(first_cell, line, rest_of_row)`` for every row of every
    markdown table whose header row's FIRST cell equals ``header``
    (case-insensitive). Tolerates the escaped-pipe (``\\|``) cells the
    catalogs use inside label enumerations."""
    out: List[Tuple[str, int, str]] = []
    lines = text.splitlines()
    in_table = False
    for i, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in
                 re.split(r"(?<!\\)\|", line.strip("|"))]
        if not cells:
            continue
        if not in_table:
            if cells[0].strip("* ").lower() == header.lower():
                in_table = True     # header row; separator row follows
            continue
        if set(cells[0]) <= {"-", ":", " "}:
            continue                # the |---|---| separator
        out.append((cells[0], i,
                    " | ".join(cells[1:]) if len(cells) > 1 else ""))
    return out


def _cell_tokens(cell: str) -> List[str]:
    toks = _BACKTICK_RE.findall(cell)
    return toks if toks else [cell.strip()]


def parse_metric_catalog(path: str) -> Dict[str, DocEntry]:
    """OBSERVABILITY.md "Metric catalog": family name (brace-stripped)
    -> DocEntry with the documented label keys. A `/`-separated cell
    documents several families in one row; duplicate rows merge."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    out: Dict[str, DocEntry] = {}
    for cell, line, rest in md_table_column(text, path, "metric"):
        for tok in _cell_tokens(cell):
            name, _, braces = tok.partition("{")
            name = name.strip()
            if not re.match(r"[a-z][a-z0-9_]*\Z", name):
                continue
            keys = tuple(_LABEL_KEY_RE.findall(braces))
            prev = out.get(name)
            if prev is None:
                out[name] = DocEntry(name, path, line, keys, rest)
            else:
                prev.label_keys = tuple(sorted(set(prev.label_keys)
                                               | set(keys)))
    return out


def parse_conf_catalog(path: str) -> Dict[str, DocEntry]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    out: Dict[str, DocEntry] = {}
    for cell, line, rest in md_table_column(text, path, "key"):
        for tok in _cell_tokens(cell):
            if _CONF_KEY_RE.match(tok):
                out.setdefault(tok, DocEntry(tok, path, line, (), rest))
    return out


def parse_site_catalog(path: str) -> Dict[str, DocEntry]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    out: Dict[str, DocEntry] = {}
    for cell, line, rest in md_table_column(text, path, "site"):
        for tok in _cell_tokens(cell):
            if _SITE_RE.match(tok):
                out.setdefault(tok, DocEntry(tok, path, line, (), rest))
    return out


def parse_rule_catalog(path: str) -> Dict[str, DocEntry]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    out: Dict[str, DocEntry] = {}
    for cell, line, rest in md_table_column(text, path, "id"):
        for tok in _cell_tokens(cell):
            if _RULE_ID_RE.match(tok):
                out.setdefault(tok, DocEntry(tok, path, line, (), rest))
    return out


# ---------------------------------------------------------------------------
# catalog location
# ---------------------------------------------------------------------------

#: surface -> catalog file name; looked up under <docs_root>/docs/guides,
#: <docs_root>/docs, then <docs_root> itself (the drift-fixture layout)
CATALOG_FILES = {
    "metrics": "OBSERVABILITY.md",
    "conf": "CONFIG.md",
    "faults": "RELIABILITY.md",
    "rules": "STATIC_ANALYSIS.md",
    "collectives": "PARALLELISM.md",
}


def find_catalog(docs_root: str, surface: str) -> Optional[str]:
    name = CATALOG_FILES[surface]
    for sub in (os.path.join("docs", "guides"), "docs", ""):
        p = os.path.join(docs_root, sub, name) if sub \
            else os.path.join(docs_root, name)
        if os.path.isfile(p):
            return p
    return None


def _missing_catalog(rule: "ProjectRule", project: ProjectContext,
                     surface: str) -> Finding:
    return Finding(
        rule.id, ERROR,
        os.path.join(project.docs_root or ".", CATALOG_FILES[surface]), 1,
        f"{CATALOG_FILES[surface]} catalog not found under "
        f"{project.docs_root!r} — the {surface} contract surface cannot "
        f"be reconciled (pass --docs-root or create the catalog)")


# ---------------------------------------------------------------------------
# reconciliation rules (project pass)
# ---------------------------------------------------------------------------

def _wildcard_match(pattern: str, value: str) -> bool:
    """Match an inexact (f-string-folded) name whose holes are ``*``."""
    rx = ".*".join(re.escape(p) for p in pattern.split("*"))
    return re.match(rx + r"\Z", value) is not None


@register_project
class ConfKeyHygiene(ProjectRule):
    """**Conf-key hygiene (code↔code).** A ``zoo.*`` key read anywhere
    that has no ``DEFAULT_CONF`` entry silently evaluates to the call
    site's fallback — a typo'd or undeclared key ships as a no-op knob
    (``zoo.seq.mode`` ran undeclared for three PRs exactly this way).
    The reverse — a ``DEFAULT_CONF`` entry no code reads — is dead
    configuration that keeps a stale promise in docs and env parsing.
    Needs the whole-package read census, which no per-file rule can
    see."""

    id = "ZL016"
    severity = ERROR

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        defaults: Dict[str, ConfDefault] = {}
        for ctx in project.modules:
            for d in conf_defaults(ctx):
                defaults.setdefault(d.key, d)
        if not defaults:
            return      # no defaults table in this tree: nothing to hold
        reads: Dict[str, ConfRead] = {}
        read_keys: Set[str] = set()
        for ctx in project.modules:
            for r in iter_conf_reads(ctx, project=project):
                reads.setdefault(r.key, r)
                read_keys.add(r.key)
        for key, r in sorted(reads.items()):
            if key not in defaults:
                yield Finding(
                    self.id, ERROR, r.path, r.line,
                    f"conf key '{key}' is read here but has no "
                    f"DEFAULT_CONF entry — an undeclared knob: env/yaml "
                    f"spellings cannot canonicalize and the default "
                    f"lives only at this call site")
        for key, d in sorted(defaults.items()):
            if key not in read_keys:
                yield Finding(
                    self.id, ERROR, d.path, d.line,
                    f"DEFAULT_CONF entry '{key}' is never read anywhere "
                    f"in the package — dead configuration (remove it or "
                    f"wire the consumer)")


@register_project
class MetricCatalogDrift(ProjectRule):
    """**Metric catalog reconciliation (code↔OBSERVABILITY.md).** Every
    registered metric family must have a catalog row and vice versa,
    and the documented label keys must match the registered ones — the
    catalog is what operators alert on; a family missing from it is
    invisible in practice, and a stale row is an alert that can never
    fire."""

    id = "ZL017"
    severity = ERROR

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        path = project.catalog_path("metrics")
        if path is None:
            yield _missing_catalog(self, project, "metrics")
            return
        doc = parse_metric_catalog(path)
        code: Dict[str, List[MetricSite]] = {}
        inexact: List[MetricSite] = []
        for ctx in project.modules:
            for s in iter_metric_sites(ctx):
                if s.name is None:
                    continue    # ZL015 reports unresolvable names
                if s.exact:
                    code.setdefault(s.name, []).append(s)
                else:
                    inexact.append(s)
        covered: Set[str] = set()
        for name, sites in sorted(code.items()):
            s = sites[0]
            if name not in doc:
                yield Finding(
                    self.id, ERROR, s.path, s.line,
                    f"metric family '{name}' is registered here but has "
                    f"no row in {os.path.basename(path)}'s metric "
                    f"catalog — add one (name, type, meaning)")
                continue
            covered.add(name)
            if any(st.opaque_labels for st in sites):
                # some registration's labels are opaque to the scan
                # (ZL015 flags the site); key comparison would be a
                # guess — compare only what resolved
                continue
            code_keys = sorted({k for st in sites for k in st.label_keys})
            doc_keys = sorted(doc[name].label_keys)
            if doc_keys != code_keys:
                yield Finding(
                    self.id, ERROR, s.path, s.line,
                    f"metric family '{name}' is registered with label "
                    f"keys {code_keys} but cataloged with {doc_keys} "
                    f"({os.path.basename(path)}:{doc[name].line})")
        for s in inexact:
            hits = [n for n in doc if _wildcard_match(s.name, n)]
            if hits:
                covered.update(hits)
            else:
                yield Finding(
                    self.id, ERROR, s.path, s.line,
                    f"metric family pattern '{s.name}' (f-string name) "
                    f"matches no row in {os.path.basename(path)}'s "
                    f"metric catalog")
        for name, entry in sorted(doc.items()):
            if name not in covered:
                yield Finding(
                    self.id, ERROR, entry.path, entry.line,
                    f"metric family '{name}' is cataloged here but no "
                    f"registration exists in the package — prune the "
                    f"row or restore the metric")


@register_project
class ConfCatalogDrift(ProjectRule):
    """**Conf catalog reconciliation (DEFAULT_CONF↔CONFIG.md).** Every
    bundled default needs a CONFIG.md row (the operator-facing
    reference) and every documented key a default — a documented knob
    with no entry cannot be spelled via env/kwargs canonicalization, a
    defaulted knob with no row is unusable in practice."""

    id = "ZL018"
    severity = ERROR

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        path = project.catalog_path("conf")
        if path is None:
            yield _missing_catalog(self, project, "conf")
            return
        doc = parse_conf_catalog(path)
        defaults: Dict[str, ConfDefault] = {}
        for ctx in project.modules:
            for d in conf_defaults(ctx):
                defaults.setdefault(d.key, d)
        for key, d in sorted(defaults.items()):
            if key not in doc:
                yield Finding(
                    self.id, ERROR, d.path, d.line,
                    f"conf key '{key}' has a DEFAULT_CONF entry but no "
                    f"row in {os.path.basename(path)} — document it "
                    f"(key, default, meaning)")
        for key, entry in sorted(doc.items()):
            if key not in defaults:
                yield Finding(
                    self.id, ERROR, entry.path, entry.line,
                    f"conf key '{key}' is documented here but has no "
                    f"DEFAULT_CONF entry in the package — prune the row "
                    f"or add the default")


@register_project
class FaultSiteCatalogDrift(ProjectRule):
    """**Fault-site reconciliation (code↔RELIABILITY.md↔tests/).**
    Chaos plans target sites by name; a site missing from the catalog
    is un-plannable, and a cataloged site no code fires makes a chaos
    plan silently test nothing (its specs never fire and ``plan.fired``
    reconciliation hides the gap only if the test author notices). The
    third direction (on when a tests root is configured) closes the
    loop: every injected site must appear in the tests tree's string
    census — a new fault site without deterministic chaos coverage
    fails ``--contracts`` instead of riding on reviewer discipline."""

    id = "ZL019"
    severity = ERROR

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        path = project.catalog_path("faults")
        if path is None:
            yield _missing_catalog(self, project, "faults")
            return
        doc = parse_site_catalog(path)
        code: Dict[str, FaultSite] = {}
        inexact: List[FaultSite] = []
        for ctx in project.modules:
            for s in iter_fault_sites(ctx, project=project):
                if s.site is None:
                    continue
                if s.exact:
                    code.setdefault(s.site, s)
                else:
                    inexact.append(s)
        covered: Set[str] = set()
        for site, s in sorted(code.items()):
            if site in doc:
                covered.add(site)
            else:
                yield Finding(
                    self.id, ERROR, s.path, s.line,
                    f"fault site '{site}' is injected here but has no "
                    f"row in {os.path.basename(path)}'s fault-site "
                    f"catalog — add one (site, fired by)")
        for s in inexact:
            hits = [n for n in doc if _wildcard_match(s.site, n)]
            if hits:
                covered.update(hits)
            else:
                yield Finding(
                    self.id, ERROR, s.path, s.line,
                    f"fault-site pattern '{s.site}' (f-string) matches "
                    f"no row in {os.path.basename(path)}'s catalog")
        for site, entry in sorted(doc.items()):
            if site not in covered:
                yield Finding(
                    self.id, ERROR, entry.path, entry.line,
                    f"fault site '{site}' is cataloged here but no "
                    f"faults.inject call fires it — prune the row or "
                    f"restore the instrumentation")
        # third direction (needs a tests root): every package site must
        # be EXERCISED by at least one test — the ROADMAP's
        # deterministic-chaos-coverage convention, machine-checked. A
        # chaos plan necessarily spells the site name as a string
        # (`plan.add("backend.xread", ...)`), so a site absent from the
        # tests tree's string census ships a recovery path no test runs.
        census = project.tests_string_census()
        if census is not None:
            for site, s in sorted(code.items()):
                if site not in census:
                    yield Finding(
                        self.id, ERROR, s.path, s.line,
                        f"fault site '{site}' is injected here but no "
                        f"test mentions it — add deterministic chaos "
                        f"coverage (a FaultPlan targeting '{site}' with "
                        f"an exact plan.fired reconciliation) so the "
                        f"recovery path does not ship untested")


@register_project
class RuleCatalogDrift(ProjectRule):
    """**Rule catalog reconciliation (code↔STATIC_ANALYSIS.md).** Every
    zoolint rule class must have a STATIC_ANALYSIS.md table row with a
    matching severity, and every documented id a declaration — the
    table is the contract ``--list-rules`` and suppression reviews are
    held against. ``ZL000`` (the reserved unparseable-file id) is
    documented in prose and exempt."""

    id = "ZL020"
    severity = ERROR

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        path = project.catalog_path("rules")
        if path is None:
            yield _missing_catalog(self, project, "rules")
            return
        doc = parse_rule_catalog(path)
        code: Dict[str, RuleDecl] = {}
        for ctx in project.modules:
            for r in iter_rule_decls(ctx):
                code.setdefault(r.rule_id, r)
        for rid, r in sorted(code.items()):
            if rid == "ZL000":
                continue
            if rid not in doc:
                yield Finding(
                    self.id, ERROR, r.path, r.line,
                    f"rule {rid} is declared here but has no row in "
                    f"{os.path.basename(path)}'s rule table")
                continue
            # compare against the severity CELL only — rule
            # descriptions routinely contain both words ("error in
            # serving/, warning elsewhere"), which would make a
            # whole-row substring check vacuously pass
            sev_cell = doc[rid].row.split(" | ")[0].lower()
            if r.severity and r.severity not in sev_cell:
                yield Finding(
                    self.id, ERROR, r.path, r.line,
                    f"rule {rid} declares severity '{r.severity}' but "
                    f"its {os.path.basename(path)} row "
                    f"(line {doc[rid].line}) severity cell says "
                    f"{sev_cell!r}")
        for rid, entry in sorted(doc.items()):
            if rid != "ZL000" and rid not in code:
                yield Finding(
                    self.id, ERROR, entry.path, entry.line,
                    f"rule {rid} is documented here but no rule class "
                    f"declares it — prune the row or restore the rule")


@register_project
class AlertRulesetCatalogDrift(ProjectRule):
    """**Alert-ruleset reconciliation (code↔OBSERVABILITY.md).** Every
    alert rule constructed inside ``default_ruleset()`` (an
    ``AlertRule(...)`` call or any ``*_rule(...)`` factory whose first
    argument is the rule name) must have a row in OBSERVABILITY.md's
    default-ruleset table (header ``rule``), and every documented rule
    name a construction site — the table is the page/warn contract
    operators hold the fleet plane to, and a silently-added or
    silently-dropped rule is an undocumented page (or a documented one
    that never fires)."""

    id = "ZL029"
    severity = ERROR

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        path = project.catalog_path("metrics")
        if path is None:
            yield _missing_catalog(self, project, "metrics")
            return
        code: Dict[str, Tuple[str, int]] = {}
        for ctx in project.modules:
            for fn in ast.walk(ctx.tree):
                if not (isinstance(fn, ast.FunctionDef)
                        and fn.name == "default_ruleset"):
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    leaf = dotted(node.func)
                    leaf = leaf.rsplit(".", 1)[-1] if leaf else ""
                    if leaf != "AlertRule" and not leaf.endswith("_rule"):
                        continue
                    if not (node.args
                            and isinstance(node.args[0], ast.Constant)
                            and isinstance(node.args[0].value, str)):
                        continue
                    code.setdefault(node.args[0].value,
                                    (ctx.path, node.lineno))
        with open(path, encoding="utf-8") as f:
            text = f.read()
        doc: Dict[str, Tuple[str, int]] = {}
        for cell, line, _rest in md_table_column(text, path, "rule"):
            for tok in _cell_tokens(cell):
                if re.match(r"[a-z][a-z0-9_]*\Z", tok):
                    doc.setdefault(tok, (path, line))
        for name, (cpath, cline) in sorted(code.items()):
            if name not in doc:
                yield Finding(
                    self.id, ERROR, cpath, cline,
                    f"alert rule '{name}' is built by default_ruleset "
                    f"but has no row in {os.path.basename(path)}'s "
                    f"default-ruleset table — an undocumented page")
        for name, (dpath, dline) in sorted(doc.items()):
            if name not in code:
                yield Finding(
                    self.id, ERROR, dpath, dline,
                    f"alert rule '{name}' is documented here but "
                    f"default_ruleset no longer builds it — prune the "
                    f"row or restore the rule")
