"""zoolint core — the AST machinery behind ``analytics_zoo_tpu.analysis``.

The linter is pure ``ast`` (no jax import, no code execution): a
:class:`ModuleContext` parses one file and pre-computes the facts every
rule needs — parent links, which functions are staged by ``jit``/``pjit``/
``pmap`` (decorator form *and* the ``fn = jax.jit(fn, ...)`` call form this
codebase prefers), which functions are ``lax.scan``/``fori_loop`` bodies,
and what local aliases ``jax.random`` / ``numpy`` are imported under.

Rules are small classes registered via :func:`register`; each yields
:class:`Finding` objects. Suppression is line-scoped: a finding is dropped
when its anchor line carries ``# zoolint: disable=ZLxxx[,ZLyyy]`` (or a
blanket ``# zoolint: disable``).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import time
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

ERROR = "error"
WARNING = "warning"

_SUPPRESS_RE = re.compile(
    r"#\s*zoolint:\s*disable"
    r"(?:\s*(?P<eq>=)\s*(?P<ids>ZL\d+(?:\s*,\s*ZL\d+)*)?)?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source line."""

    rule_id: str
    severity: str           # ERROR | WARNING
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.severity} " \
               f"{self.rule_id} {self.message}"


@dataclasses.dataclass
class JitInfo:
    """How a function is staged: which params are static, whether any
    buffer donation is declared, and where the jit wrapping happens (the
    decorator line or the ``jax.jit(fn, ...)`` call line — suppression
    comments for staging-level rules go there)."""

    fn: ast.AST                      # FunctionDef / AsyncFunctionDef
    static_names: Set[str]
    donates: bool
    anchor_line: int


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_JIT_DOTTED = {"jit", "pjit", "pmap"}


def _is_partial(node: ast.AST) -> bool:
    d = dotted(node)
    return d in ("partial", "functools.partial")


def _const_strs(node: ast.AST) -> List[str]:
    """String constants in a literal or tuple/list of literals."""
    out: List[str] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            out.extend(_const_strs(e))
    return out


def _const_ints(node: ast.AST) -> List[int]:
    out: List[int] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            out.extend(_const_ints(e))
    return out


def folded_str(node: ast.AST) -> Optional[Tuple[str, bool]]:
    """``(text, exact)`` for a string-valued expression the analyzer can
    fold statically: a plain constant (exact), an f-string whose
    formatted holes become ``*`` wildcards (inexact), or a ``+``
    concatenation of foldable parts. None for anything else. The metric
    and fault-site extractors use this so a constant-folded or f-string
    name still reconciles against its catalog instead of silently
    dropping out of the scan."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, True
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        exact = True
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
                exact = False
        return "".join(parts), exact
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = folded_str(node.left)
        right = folded_str(node.right)
        if left is not None and right is not None:
            return left[0] + right[0], left[1] and right[1]
    return None


def param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


_SCOPE_NODES = (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef,
                ast.ClassDef)


class ModuleContext:
    """Parsed module + the shared facts rules query."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._zl_parent = parent  # type: ignore[attr-defined]
        self._jitted: Optional[Dict[int, JitInfo]] = None
        self._scan_bodies: Optional[List[ast.AST]] = None
        self._aliases: Optional[Dict[str, Set[str]]] = None
        self._from_imports: Dict[str, Dict[str, str]] = {}
        self._jit_names_cache: Optional[Tuple[Set[str], Set[str]]] = None
        self._jax_names_cache: Optional[Tuple[Set[str],
                                              Dict[str, str]]] = None
        self._comments: Optional[Dict[int, str]] = None
        self._stmt_starts: Optional[Dict[int, int]] = None

    # -- generic helpers ----------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_zl_parent", None)

    def in_nested_scope(self, node: ast.AST, fn: ast.AST) -> bool:
        """Whether ``node`` sits inside a def/lambda nested WITHIN ``fn``
        — a separate runtime scope whose parameters shadow ``fn``'s, so
        per-function rules must not attribute its statements to ``fn``."""
        cur = self.parent(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return True
            cur = self.parent(cur)
        return False

    def functions(self) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    # -- import aliases -----------------------------------------------------
    @property
    def aliases(self) -> Dict[str, Set[str]]:
        """Local dotted-prefix aliases for the modules rules care about:
        ``{"jax.random": {"jax.random", "jrandom", ...},
           "numpy": {"numpy", "np", ...},
           "jax.numpy": {"jax.numpy", "jnp", ...}}``."""
        if self._aliases is not None:
            return self._aliases
        al = {"jax.random": {"jax.random"},
              "numpy": {"numpy"},
              "jax.numpy": {"jax.numpy"},
              "time": {"time"},
              "queue": {"queue"},
              "threading": {"threading"},
              "logging": {"logging"}}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in al:
                        al[a.name].add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    if full in al:
                        al[full].add(a.asname or a.name)
        self._aliases = al
        return al

    def from_imported(self, module: str) -> Dict[str, str]:
        """``local name -> original name`` for every
        ``from <module> import x [as y]`` in this file — how rules catch
        a bare ``perf_counter()`` that is really ``time.perf_counter``."""
        if module in self._from_imports:
            return self._from_imports[module]
        out: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module == module:
                for a in node.names:
                    out[a.asname or a.name] = a.name
        self._from_imports[module] = out
        return out

    def is_call_to(self, call_dotted: Optional[str], module: str,
                   names: Iterable[str]) -> Optional[str]:
        """If ``call_dotted`` is ``<alias of module>.<one of names>``,
        return the bare name, else None."""
        if not call_dotted or "." not in call_dotted:
            return None
        prefix, leaf = call_dotted.rsplit(".", 1)
        if leaf in names and prefix in self.aliases.get(module, ()):
            return leaf
        return None

    @property
    def jax_names(self) -> Tuple[Set[str], Dict[str, str]]:
        """``(module_aliases, from_imported)`` for the jax package:
        local names bound to a jax module (``import jax``, ``import
        jax.numpy as jnp``, ``from jax import sharding``) and ``local ->
        original`` for every ``from jax[.x] import name [as alias]``.
        Rules that flag by call-name (``Mesh``, ``devices``) resolve
        through this so a non-JAX ``trimesh.Mesh(...)`` or a local
        ``make_mesh()`` is never mistaken for backend-pinning JAX API."""
        if self._jax_names_cache is not None:
            return self._jax_names_cache
        mods: Set[str] = set()
        froms: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        # `import jax.numpy` binds `jax`; with an asname
                        # the alias is the submodule itself
                        mods.add(a.asname if a.asname else "jax")
            elif isinstance(node, ast.ImportFrom) and node.module and (
                    node.module == "jax"
                    or node.module.startswith("jax.")):
                for a in node.names:
                    local = a.asname or a.name
                    froms[local] = a.name
                    # `from jax import sharding` binds a module too —
                    # statically indistinguishable from a function import,
                    # so the local name joins both sets
                    mods.add(local)
        self._jax_names_cache = (mods, froms)
        return self._jax_names_cache

    # -- jit / scan-body discovery ------------------------------------------
    @property
    def _jit_names(self) -> Tuple[Set[str], Set[str]]:
        """``(prefixes, bare)`` — local names resolving to a jax module
        that carries jit/pjit/pmap, and bare names from-imported off a jax
        module. Import-resolved so ``@numba.jit`` or a ``self.jit(...)``
        method is NOT mistaken for JAX staging (the under-jit rules are
        error-severity; precision matters on arbitrary user files)."""
        if self._jit_names_cache is not None:
            return self._jit_names_cache
        prefixes: Set[str] = set()
        bare: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        prefixes.add(a.asname or "jax")
                    elif a.name.startswith("jax."):
                        prefixes.add(a.asname or a.name)
                        if a.asname is None:
                            prefixes.add("jax")   # `import jax.x` binds jax
            elif isinstance(node, ast.ImportFrom) and node.module and (
                    node.module == "jax"
                    or node.module.startswith("jax.")):
                for a in node.names:
                    if a.name in _JIT_DOTTED:
                        bare.add(a.asname or a.name)
                    else:   # e.g. `from jax.experimental import pjit`
                        prefixes.add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom) and node.module and (
                    node.module in ("observability", "observability.compile")
                    or node.module.endswith(".observability")
                    or node.module.endswith(".observability.compile")):
                # the in-repo jit wrapper stages its argument exactly like
                # jax.jit (observability/compile.py) — functions handed to
                # it must stay covered by the under-jit rules
                for a in node.names:
                    if a.name == "instrument_jit":
                        bare.add(a.asname or a.name)
        self._jit_names_cache = (prefixes, bare)
        return self._jit_names_cache

    def _is_jit(self, node: ast.AST) -> bool:
        d = dotted(node)
        if d is None:
            return False
        prefixes, bare = self._jit_names
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            return leaf in _JIT_DOTTED and prefix in prefixes
        return d in bare

    def _jit_kwargs(self, keywords, fn) -> Tuple[Set[str], bool]:
        statics: Set[str] = set()
        donates = False
        names = param_names(fn)
        for kw in keywords:
            if kw.arg in ("static_argnames",):
                statics.update(_const_strs(kw.value))
            elif kw.arg in ("static_argnums", "static_broadcasted_argnums"):
                for i in _const_ints(kw.value):
                    if 0 <= i < len(names):
                        statics.add(names[i])
            elif kw.arg in ("donate_argnums", "donate_argnames"):
                donates = True
        return statics, donates

    def _enclosing_scope(self, node: ast.AST) -> ast.AST:
        cur = self.parent(node)
        while cur is not None and not isinstance(cur, _SCOPE_NODES):
            cur = self.parent(cur)
        return cur if cur is not None else self.tree

    @staticmethod
    def _scope_bound_names(scope: ast.AST) -> Set[str]:
        """Names bound inside ``scope`` by parameters or assignment-like
        statements (not nested defs' locals) — anything here SHADOWS a
        same-named outer function for Name lookups in this scope."""
        out: Set[str] = set()
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = scope.args
            for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
                out.add(p.arg)
            if a.vararg:
                out.add(a.vararg.arg)
            if a.kwarg:
                out.add(a.kwarg.arg)

        def targets(node):
            if isinstance(node, ast.Name):
                out.add(node.id)
            elif isinstance(node, (ast.Tuple, ast.List)):
                for e in node.elts:
                    targets(e)
            elif isinstance(node, ast.Starred):
                targets(node.value)

        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue    # nested scope: its locals don't shadow here
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    targets(t)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                   ast.NamedExpr)):
                targets(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                targets(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        targets(item.optional_vars)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                out.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for al in node.names:
                    out.add((al.asname or al.name).split(".", 1)[0])
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _resolve_local_fn(self, call: ast.Call,
                          name: str) -> Optional[ast.AST]:
        """The FunctionDef ``name`` refers to at ``call``, searching the
        chain of lexically enclosing scopes. A function scope that REBINDS
        ``name`` — parameter or local assignment — ends the search
        unresolved: in ``def compile_step(step): return jax.jit(step)``
        (or ``step = make(); jax.jit(step)``) the jitted thing is the
        local value, not an unrelated same-named outer function."""
        scope = self._enclosing_scope(call)
        while scope is not None:
            for node in ast.walk(scope):
                if (isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                        and node.name == name
                        and self._enclosing_scope(node) is scope):
                    return node
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and name in self._scope_bound_names(scope):
                return None
            if isinstance(scope, ast.Module):
                return None
            scope = self._enclosing_scope(scope)
        return None

    @property
    def jitted(self) -> Dict[int, JitInfo]:
        """``id(fn_node) -> JitInfo`` for every function this module stages
        with jit/pjit/pmap — via decorator (``@jax.jit``,
        ``@partial(jax.jit, ...)``) or via the call form
        (``self._step = jax.jit(step, donate_argnums=...)``)."""
        if self._jitted is not None:
            return self._jitted
        out: Dict[int, JitInfo] = {}

        def add(fn, keywords, anchor_line):
            statics, donates = self._jit_kwargs(keywords, fn)
            info = out.get(id(fn))
            if info is None:
                out[id(fn)] = JitInfo(fn, statics, donates, anchor_line)
            else:   # jitted twice: merge (stay conservative on donation)
                info.static_names |= statics
                info.donates = info.donates or donates

        for fn in self.functions():
            for dec in fn.decorator_list:
                if self._is_jit(dec):
                    add(fn, [], fn.lineno)
                elif isinstance(dec, ast.Call):
                    if self._is_jit(dec.func):
                        add(fn, dec.keywords, fn.lineno)
                    elif (_is_partial(dec.func) and dec.args
                          and self._is_jit(dec.args[0])):
                        add(fn, dec.keywords, fn.lineno)
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Call) and self._is_jit(node.func)
                    and node.args and isinstance(node.args[0], ast.Name)):
                fn = self._resolve_local_fn(node, node.args[0].id)
                if fn is not None:
                    add(fn, node.keywords, node.lineno)
        self._jitted = out
        return out

    @property
    def scan_bodies(self) -> List[ast.AST]:
        """Function/lambda nodes passed to ``lax.scan`` / ``lax.fori_loop``
        / ``lax.while_loop`` / ``lax.map`` — their bodies are traced even
        outside any jit, so the host-sync rules cover them too."""
        if self._scan_bodies is not None:
            return self._scan_bodies
        out: List[ast.AST] = []
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not d or d.rsplit(".", 1)[-1] not in (
                    "scan", "fori_loop", "while_loop", "map", "cond"):
                continue
            if "lax" not in d.split("."):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    out.append(arg)
                elif isinstance(arg, ast.Name):
                    fn = self._resolve_local_fn(node, arg.id)
                    if fn is not None:
                        out.append(fn)
        self._scan_bodies = out
        return out

    # -- suppression --------------------------------------------------------
    @property
    def comments(self) -> Dict[int, str]:
        """``line -> comment text`` — tokenized so a STRING LITERAL that
        happens to contain ``# zoolint: disable`` can never suppress a
        real finding on its line."""
        if self._comments is None:
            out: Dict[int, str] = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        out[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError, SyntaxError):
                # ast.parse succeeded, so this is near-unreachable; degrade
                # to raw lines (over-suppression beats a crashed scan)
                out = {i + 1: ln for i, ln in enumerate(self.lines)}
            self._comments = out
        return self._comments

    @property
    def stmt_starts(self) -> Dict[int, int]:
        """``physical line -> first line of the innermost multi-line
        STATEMENT covering it`` — a ``# zoolint: disable`` on the line a
        multi-line call starts on must also cover findings a rule
        anchors to a later physical line of the same statement (e.g. a
        ``labels={...}`` keyword three lines into a registration call).
        Innermost wins so a suppression on an outer ``with`` does not
        blanket every statement in its body."""
        if self._stmt_starts is None:
            out: Dict[int, int] = {}
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt):
                    continue
                end = getattr(node, "end_lineno", None) or node.lineno
                if end <= node.lineno:
                    continue
                # body statements of a compound node map to themselves on
                # a later pass; only the header span belongs to it. For
                # simple multi-line statements (Assign/Expr/Return...)
                # the whole range is the statement.
                if isinstance(node, (ast.If, ast.For, ast.AsyncFor,
                                     ast.While, ast.With, ast.AsyncWith,
                                     ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef, ast.Try, ast.Match)):
                    continue
                for ln in range(node.lineno, end + 1):
                    prev = out.get(ln)
                    # innermost statement wins: a later (= more deeply
                    # nested or more specific) start line replaces an
                    # earlier one only when it starts later
                    if prev is None or node.lineno > prev:
                        out[ln] = node.lineno
            self._stmt_starts = out
        return self._stmt_starts

    def _comment_suppresses(self, line: int, rule_id: str) -> bool:
        comment = self.comments.get(line)
        if not comment:
            return False
        m = _SUPPRESS_RE.search(comment)
        if not m:
            return False
        ids = m.group("ids")
        if ids is None:
            # bare `# zoolint: disable` is a blanket suppression, but
            # `disable=<not-a-rule-id>` is a typo, not a blanket
            return m.group("eq") is None
        # trailing prose after the id list (`disable=ZL001 key reuse is
        # fine here`) is a justification, not part of the ids
        return rule_id in {s.strip() for s in ids.split(",")}

    def suppressed(self, finding: Finding) -> bool:
        if self._comment_suppresses(finding.line, finding.rule_id):
            return True
        # a marker on the FIRST line of a multi-line statement covers
        # findings anchored to any later physical line of that statement
        start = self.stmt_starts.get(finding.line)
        if start is not None and start != finding.line:
            return self._comment_suppresses(start, finding.rule_id)
        return False


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

class Rule:
    """One check. Subclasses set ``id``/``severity``/``__doc__`` and
    implement :meth:`check`. ``severity`` is the default — rules may emit
    findings at a different severity (e.g. ZL007 escalates by path)."""

    id: str = ""
    severity: str = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, line: int, message: str,
                severity: Optional[str] = None) -> Finding:
        return Finding(self.id, severity or self.severity, ctx.path,
                       line, message)


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding one rule instance to the global registry."""
    if not cls.id:
        raise ValueError(f"{cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> List[Rule]:
    from . import rules  # noqa: F401  (registers on first import)
    from . import device  # noqa: F401  (device-semantics rules ZL021-ZL024)
    from . import spmd  # noqa: F401  (SPMD collective rules ZL025-ZL028)
    return sorted(_REGISTRY.values(), key=lambda r: r.id)


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def _zl000_kept(select: Optional[Iterable[str]],
                ignore: Optional[Iterable[str]]) -> bool:
    """select/ignore apply to ZL000 like any rule id — `--ignore ZL000`
    must actually drop unparseable-file findings, not no-op."""
    if select is not None and "ZL000" not in set(select):
        return False
    return "ZL000" not in set(ignore or ())


def lint_context(ctx: ModuleContext,
                 select: Optional[Iterable[str]] = None,
                 ignore: Optional[Iterable[str]] = None,
                 profile: Optional[Dict[str, float]] = None
                 ) -> List[Finding]:
    """All non-suppressed per-file findings for an ALREADY-PARSED module
    — the reuse surface the ``--contracts`` CLI path goes through so the
    project pass and the per-file rules share one parse per file.
    ``profile`` (a dict the caller owns) accumulates per-rule wall-clock
    seconds across every file — the ``--profile`` surface that keeps
    slow rules visible before they bloat the tier-1 gate."""
    select = set(select) if select else None
    ignore = set(ignore) if ignore else set()
    out: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for rule in all_rules():
        if select is not None and rule.id not in select:
            continue
        if rule.id in ignore:
            continue
        t0 = time.perf_counter() if profile is not None else 0.0
        found = list(rule.check(ctx))
        if profile is not None:
            profile[rule.id] = profile.get(rule.id, 0.0) \
                + (time.perf_counter() - t0)
        for f in found:
            key = (f.rule_id, f.line, f.message)
            if key in seen or ctx.suppressed(f):
                continue
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.line, f.rule_id))
    return out


def lint_source(source: str, path: str = "<string>",
                select: Optional[Iterable[str]] = None,
                ignore: Optional[Iterable[str]] = None,
                profile: Optional[Dict[str, float]] = None
                ) -> List[Finding]:
    """All non-suppressed findings for one module's source text."""
    try:
        ctx = ModuleContext(path, source)
    # ValueError: ast.parse rejects e.g. null bytes without a SyntaxError
    except (SyntaxError, ValueError) as e:
        if not _zl000_kept(select, ignore):
            return []
        return [Finding("ZL000", ERROR, path, getattr(e, "lineno", 1) or 1,
                        f"syntax error: {getattr(e, 'msg', None) or e}")]
    return lint_context(ctx, select=select, ignore=ignore, profile=profile)


def lint_file(path: str, **kw) -> List[Finding]:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    # one unreadable/non-UTF8 file must degrade to a finding, not abort
    # the whole gate scan with every later file unscanned
    except (OSError, UnicodeDecodeError) as e:
        if not _zl000_kept(kw.get("select"), kw.get("ignore")):
            return []
        return [Finding("ZL000", ERROR, path, 1, f"cannot read: {e}")]
    return lint_source(source, path, **kw)


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    # overlapping arguments (`zoolint pkg/ pkg/x.py`) must not lint a file
    # twice — every finding would print and count double
    seen: Set[str] = set()

    def fresh(p: str) -> bool:
        rp = os.path.realpath(p)
        if rp in seen:
            return False
        seen.add(rp)
        return True

    for p in paths:
        if os.path.isfile(p):
            if fresh(p):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py") and \
                            fresh(os.path.join(root, name)):
                        yield os.path.join(root, name)


def lint_paths(paths: Iterable[str], **kw) -> List[Finding]:
    out: List[Finding] = []
    for path in iter_py_files(paths):
        out.extend(lint_file(path, **kw))
    return out
