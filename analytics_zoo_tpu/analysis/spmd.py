"""zoolint SPMD collective-semantics pass — rules ZL025–ZL028.

The device pass (ZL021–ZL024) checks dtype flow, mesh-axis vocabulary
and Pallas tile geometry but is blind to the COLLECTIVE layer itself:
what a ``psum``/``ppermute``/``all_gather`` does to a value's
distribution across the mesh, and whether a ``shard_map`` body's
``out_specs`` claim matches what the body actually produced. This
fourth stage closes that gap with an abstract interpreter over
``shard_map`` bodies tracking a per-value **distribution state
lattice**:

========================  ==================================================
state                     meaning (per mesh axis)
========================  ==================================================
``replicated``            every rank along the axis holds the same value
``sharded(axis, …)``      ranks hold different blocks (device-varying)
``partial_sum(axis, …)``  ranks hold unreduced partial sums — the true
                          value is the ``psum`` over the axis
``unknown``               nothing provable (the walker's default)
========================  ==================================================

Values are seeded from ``in_specs`` PartitionSpecs, transitioned by
collectives (``psum``/``pmax``/``pmin`` reduce an axis to replicated
and clear partial sums on it; ``psum_scatter`` converts partial to
sharded; ``all_gather`` un-shards; ``axis_index`` is device-varying;
``ppermute``/``all_to_all`` preserve the state) and by arithmetic
(adds/``where`` propagate, a dot whose operands are sharded over the
same axis at DIFFERENT dim positions — the Megatron row-parallel
signature — produces a partial sum over that axis). The walker reuses
``device.py``'s conventions: straight-line statement order, constant
folding through the mesh-module axis constants (ZL022's vocabulary),
one-level local-helper resolution, and *precision over recall* — an
unresolvable spec, axis or call degrades to ``unknown``, which is
never accused.

* **ZL025** — collective axis validity: a collective inside a
  ``shard_map`` body naming an axis the enclosing mesh does not bind
  fails at trace time only on a real multi-chip mesh. The project pass
  (``--contracts``) adds the collective-catalog reconciliation: every
  collective call site in ``parallel/``+``ops/`` ↔ a documented row
  (with its axis semantics) in ``docs/guides/PARALLELISM.md``, both
  directions.
* **ZL026** — unreduced-output hazard: a ``partial_sum(axis)`` value
  reaching ``out_specs`` that claim replication or sharding on that
  axis (``check_vma=False`` ships the wrong numbers silently), plus
  the caller-side form PR 14 hit in production: an in-jit computed
  operand (``jnp.stack``/``jax.tree.map`` at trace time) entering the
  manual region without a committed layout arrives unreduced
  (×axis-size) — pin it with ``with_sharding_constraint`` first.
* **ZL027** — divergent collectives under traced control flow: a
  collective reachable in only one branch of a ``lax.cond`` (or at all
  inside a ``lax.while_loop``, whose traced trip count can differ per
  rank) deadlocks the mesh — some ranks enter the collective, the
  rest never arrive. ``lax.scan`` bodies are exempt: the trip count is
  static, every rank runs the same schedule (the GPipe/ring pattern).
* **ZL028** — PartitionSpec hygiene: an axis used twice in one spec
  (jax rejects it at trace time), and provable arity mismatches at
  ``shard_map`` sites (``in_specs`` count vs the body's parameters,
  ``out_specs`` count vs the returned tuple). Axis-name vocabulary
  membership stays ZL022's job — one rule per fact.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import (Dict, FrozenSet, Iterator, List, Optional, Sequence,
                    Set, Tuple)

from .core import (ERROR, WARNING, Finding, ModuleContext, Rule, dotted,
                   register)
from .device import (_COLLECTIVES, _fold_axis_names, _in_package,
                     extract_axis_decls, package_axis_vocabulary,
                     staged_fns)
from .project import ProjectContext, ProjectRule, register_project

# ---------------------------------------------------------------------------
# the distribution-state lattice
# ---------------------------------------------------------------------------

_EMPTY: FrozenSet[str] = frozenset()


@dataclasses.dataclass(frozen=True)
class DistState:
    """Abstract distribution state of one value inside a manual
    (``shard_map``) region. ``sharded``/``partial`` are sets of mesh
    axis names; ``known=False`` is bottom-less top — nothing provable,
    never accused. ``dims`` optionally remembers which ARRAY dimension
    an axis shards (seed-time fact from the PartitionSpec) so the dot
    transfer can tell row-parallel contraction from batch sharding.
    ``elts`` carries per-element states for tuple values (``psum`` over
    an operand tuple, multi-output bodies)."""

    sharded: FrozenSet[str] = _EMPTY
    partial: FrozenSet[str] = _EMPTY
    known: bool = True
    dims: Tuple[Tuple[str, int], ...] = ()
    elts: Optional[Tuple["DistState", ...]] = None

    # -- constructors -------------------------------------------------
    @staticmethod
    def replicated() -> "DistState":
        return DistState()

    @staticmethod
    def unknown() -> "DistState":
        return DistState(known=False)

    @staticmethod
    def sharded_over(axes, dims: Optional[Dict[str, int]] = None
                     ) -> "DistState":
        return DistState(sharded=frozenset(axes),
                         dims=tuple(sorted((dims or {}).items())))

    @staticmethod
    def partial_over(axes) -> "DistState":
        return DistState(partial=frozenset(axes))

    # -- queries ------------------------------------------------------
    @property
    def is_replicated(self) -> bool:
        return self.known and not self.sharded and not self.partial

    def dim_of(self, axis: str) -> Optional[int]:
        return dict(self.dims).get(axis)

    # -- transitions --------------------------------------------------
    def reduce_over(self, axes) -> "DistState":
        """``psum``/``pmean``/``pmax``/``pmin`` over ``axes``: the
        result is replicated along them — both the sharding and any
        partial sum on those axes are resolved."""
        axes = frozenset(axes)
        return dataclasses.replace(
            self, sharded=self.sharded - axes, partial=self.partial - axes,
            elts=tuple(e.reduce_over(axes) for e in self.elts)
            if self.elts is not None else None)

    def scatter_over(self, axes) -> "DistState":
        """``psum_scatter``: partial sums reduce but the result is
        sharded over the axis."""
        axes = frozenset(axes)
        return dataclasses.replace(
            self, sharded=self.sharded | axes, partial=self.partial - axes,
            dims=(), elts=None)

    def gather_over(self, axes) -> "DistState":
        """``all_gather``: un-shards the axis; a partial sum survives
        gathering (every rank now holds all the unreduced terms)."""
        axes = frozenset(axes)
        return dataclasses.replace(self, sharded=self.sharded - axes,
                                   dims=(), elts=None)

    def drop_dims(self) -> "DistState":
        return dataclasses.replace(self, dims=()) if self.dims else self


def join(a: DistState, b: DistState) -> DistState:
    """Least upper bound used both for control-flow merges and for
    elementwise arithmetic combining (add/sub/``where``): a value that
    is device-varying or partial on EITHER input stays hazardous in the
    result; ``unknown`` absorbs everything."""
    if not a.known or not b.known:
        return DistState.unknown()
    if a.elts is not None and b.elts is not None \
            and len(a.elts) == len(b.elts):
        elts: Optional[Tuple[DistState, ...]] = tuple(
            join(x, y) for x, y in zip(a.elts, b.elts))
    else:
        elts = None
    da, db = dict(a.dims), dict(b.dims)
    if not da:
        dims = b.dims
    elif not db:
        dims = a.dims
    else:
        dims = tuple(sorted((k, v) for k, v in da.items()
                            if db.get(k) == v))
    return DistState(sharded=a.sharded | b.sharded,
                     partial=a.partial | b.partial,
                     dims=dims, elts=elts)


def join_all(states: Sequence[DistState]) -> DistState:
    out = DistState.replicated()
    for s in states:
        out = join(out, s)
    return out


def dot_transfer(a: DistState, b: DistState) -> DistState:
    """Contraction transfer (``dot``/``matmul``/``einsum``/``@``): an
    axis both operands are sharded over at provably DIFFERENT dim
    positions is being contracted across ranks (Megatron row-parallel:
    ``x@P(None, m) · w@P(m, None)``) — the local result is a partial
    sum over it. Same (or unprovable) positions mean batch-style
    sharding (the ring-attention ``bhqd·bhkd`` case) and stay sharded."""
    if not a.known or not b.known:
        return DistState.unknown()
    contracted: Set[str] = set()
    for ax in a.sharded & b.sharded:
        da, db = a.dim_of(ax), b.dim_of(ax)
        if da is not None and db is not None and da != db:
            contracted.add(ax)
    return DistState(
        sharded=(a.sharded | b.sharded) - contracted,
        partial=a.partial | b.partial | frozenset(contracted))


# ---------------------------------------------------------------------------
# PartitionSpec folding
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SpecInfo:
    """One folded PartitionSpec: per-dim axis-name tuples (``()`` for a
    ``None``/unsharded dim). ``complete=False`` means some dim did not
    resolve — the spec's KNOWN axes still seed, but nothing is accused
    against its unresolved remainder."""

    dims: Tuple[Tuple[str, ...], ...]
    complete: bool
    line: int

    def axes(self) -> FrozenSet[str]:
        return frozenset(ax for d in self.dims for ax in d)

    def dim_index(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for i, d in enumerate(self.dims):
            for ax in d:
                out.setdefault(ax, i)
        return out


@dataclasses.dataclass
class SpecList:
    """A folded ``in_specs``/``out_specs`` value: a known prefix of
    specs (``None`` entries did not fold) and whether the LENGTH itself
    is proven (conditional ``+ ((mask_spec,) if …)`` tails are not).
    ``single`` marks a lone spec, which shard_map broadcasts over every
    operand/output."""

    specs: List[Optional[SpecInfo]]
    complete: bool
    single: bool = False


def _is_pspec_call(ctx: ModuleContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    if not d:
        return False
    mods, froms = ctx.jax_names
    leaf = d.split(".")[-1]
    if leaf == "PartitionSpec":
        prefix = d.rsplit(".", 1)[0] if "." in d else ""
        return not prefix or prefix in mods or prefix.split(".", 1)[0] in mods
    return "." not in d and froms.get(d) == "PartitionSpec"


def _fold_pspec(ctx: ModuleContext, node: ast.Call,
                consts: Dict[str, str]) -> SpecInfo:
    dims: List[Tuple[str, ...]] = []
    complete = True
    for arg in node.args:
        if isinstance(arg, ast.Starred):
            complete = False
            break
        if isinstance(arg, ast.Constant):
            if arg.value is None:
                dims.append(())
            elif isinstance(arg.value, str):
                dims.append((arg.value,))
            else:
                complete = False
                dims.append(())
            continue
        if isinstance(arg, (ast.Tuple, ast.List)):
            axes: List[str] = []
            for e in arg.elts:
                ax = _resolve_axis_token(e, consts)
                if ax is None:
                    complete = False
                else:
                    axes.append(ax)
            dims.append(tuple(axes))
            continue
        ax = _resolve_axis_token(arg, consts)
        if ax is None:
            complete = False
            dims.append(())
        else:
            dims.append((ax,))
    return SpecInfo(tuple(dims), complete, node.lineno)


def _resolve_axis_token(e: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    """A mesh-axis name out of one expression: a string literal or a
    name resolving through the (in-file + mesh-module) axis constants.
    Anything else — parameters, locals — is unresolvable, by the same
    precision-over-recall stance as ``device.iter_axis_uses``."""
    if isinstance(e, ast.Constant) and isinstance(e.value, str):
        return e.value
    d = dotted(e)
    if d and d.split(".")[-1] in consts:
        return consts[d.split(".")[-1]]
    return None


def _bindings_of(ctx: ModuleContext, scope: ast.AST,
                 name: str) -> List[Tuple[ast.Assign, Optional[int]]]:
    """Assignments binding ``name`` directly in ``scope`` (not in
    nested defs): ``(assign, None)`` for a plain target, ``(assign,
    i)`` for position ``i`` of a tuple-unpack target."""
    out: List[Tuple[ast.Assign, Optional[int]]] = []
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign):
            continue
        if ctx._enclosing_scope(node) is not scope:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == name:
                out.append((node, None))
            elif isinstance(t, ast.Tuple):
                for i, e in enumerate(t.elts):
                    if isinstance(e, ast.Name) and e.id == name:
                        out.append((node, i))
    return out


def _single_binding(ctx: ModuleContext, at: ast.AST,
                    name: str) -> Optional[Tuple[ast.AST, Optional[int]]]:
    """The unique expression ``name`` is bound to, searched through the
    lexical scope chain of ``at``. Multiple bindings in the deciding
    scope → ambiguous → None (flow-insensitive honesty)."""
    scope = ctx._enclosing_scope(at)
    seen: Set[int] = set()
    while scope is not None and id(scope) not in seen:
        seen.add(id(scope))
        binds = _bindings_of(ctx, scope, name)
        if binds:
            if len(binds) != 1:
                return None
            assign, idx = binds[0]
            return assign.value, idx
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = scope.args
            params = {p.arg for p in list(a.posonlyargs) + list(a.args)
                      + list(a.kwonlyargs)}
            if a.vararg:
                params.add(a.vararg.arg)
            if a.kwarg:
                params.add(a.kwarg.arg)
            if name in params:
                return None         # a parameter shadows outer bindings
        if scope is ctx.tree:
            return None
        scope = ctx._enclosing_scope(scope)
    return None


def _helper_returns(ctx: ModuleContext, call: ast.Call) -> List[ast.AST]:
    """The return expressions of a locally-resolvable helper call
    (one level deep), or ``[]``."""
    if not isinstance(call.func, ast.Name):
        return []
    fn = ctx._resolve_local_fn(call, call.func.id)
    if fn is None or not isinstance(fn, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
        return []
    return [n.value for n in ast.walk(fn)
            if isinstance(n, ast.Return) and n.value is not None
            and ctx._enclosing_scope(n) is fn]


def fold_specs(ctx: ModuleContext, node: Optional[ast.AST],
               consts: Dict[str, str], depth: int = 0
               ) -> Optional[SpecList]:
    """Fold an ``in_specs``/``out_specs`` expression into a
    :class:`SpecList`, through the live idioms: literal ``P(...)``
    tuples, ``Name``-bound specs, one-level helper returns (the
    ``_seq_specs``/``_sharded_specs`` pattern), conditional tuple
    concatenation (known prefix, unproven length) and
    ``jax.tree.map(lambda _: P(axis), tree)`` (the gpipe per-leaf
    spec). Returns None when nothing folds."""
    if node is None or depth > 4:
        return None
    if _is_pspec_call(ctx, node):
        spec = _fold_pspec(ctx, node, consts)
        return SpecList([spec], complete=spec.complete, single=True)
    if isinstance(node, (ast.Tuple, ast.List)):
        specs: List[Optional[SpecInfo]] = []
        complete = True
        for e in node.elts:
            if isinstance(e, ast.Starred):
                return SpecList(specs, complete=False)
            sub = fold_specs(ctx, e, consts, depth + 1)
            if sub is not None and sub.single:
                specs.append(sub.specs[0])
                complete = complete and sub.complete
            else:
                specs.append(None)
                complete = False
        return SpecList(specs, complete)
    if isinstance(node, ast.Name):
        bound = _single_binding(ctx, node, node.id)
        if bound is None:
            return None
        expr, idx = bound
        if idx is None:
            return fold_specs(ctx, expr, consts, depth + 1)
        # tuple-unpack binding: `spec, in_specs = _seq_specs(mask)`
        if isinstance(expr, ast.Tuple) and idx < len(expr.elts):
            return fold_specs(ctx, expr.elts[idx], consts, depth + 1)
        if isinstance(expr, ast.Call):
            rets = _helper_returns(ctx, expr)
            if len(rets) == 1 and isinstance(rets[0], ast.Tuple) \
                    and idx < len(rets[0].elts):
                return fold_specs(ctx, rets[0].elts[idx], consts,
                                  depth + 1)
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = fold_specs(ctx, node.left, consts, depth + 1)
        if left is None:
            return None
        right = fold_specs(ctx, node.right, consts, depth + 1)
        if right is not None and right.complete and not right.single:
            return SpecList(left.specs + right.specs,
                            left.complete and right.complete)
        # conditional tail (`+ ((mask_spec,) if ... else ())`): the left
        # prefix is certain, the total length is not
        return SpecList(list(left.specs), complete=False)
    if isinstance(node, ast.IfExp):
        return None                  # two arms, no single truth
    if isinstance(node, ast.Call):
        rets = _helper_returns(ctx, node)
        if len(rets) == 1:
            return fold_specs(ctx, rets[0], consts, depth + 1)
        # `jax.tree.map(lambda _: P(axis), tree)`: the one inner P call
        # IS the per-leaf spec
        d = dotted(node.func) or ""
        parts = d.split(".")
        if parts[-1] in ("map", "tree_map") and (
                "tree" in parts or "tree_util" in parts):
            inner = [n for n in ast.walk(node)
                     if _is_pspec_call(ctx, n)]
            if len(inner) == 1:
                spec = _fold_pspec(ctx, inner[0], consts)
                return SpecList([spec], complete=spec.complete,
                                single=True)
    return None


# ---------------------------------------------------------------------------
# shard_map site discovery
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShardMapSite:
    """One ``shard_map`` entry into a manual region: the decorator form
    (``@functools.partial(compat.shard_map, mesh=…, in_specs=…,
    out_specs=…)``) or the call form (``fn = compat.shard_map(local,
    mesh=…, …)``)."""

    line: int
    body: Optional[ast.AST]          # FunctionDef/Lambda, when resolvable
    in_specs: Optional[ast.AST]
    out_specs: Optional[ast.AST]
    mesh_node: Optional[ast.AST]
    names: FrozenSet[str]            # names the wrapped callable binds to


def _is_shard_map_ref(ctx: ModuleContext, node: ast.AST) -> bool:
    d = dotted(node)
    if not d:
        return False
    leaf = d.split(".")[-1]
    if leaf != "shard_map":
        return False
    if "." in d:
        return True
    _, froms = ctx.jax_names
    return froms.get(d) == "shard_map"


def _site_kwargs(call: ast.Call, skip_args: int
                 ) -> Dict[str, Optional[ast.AST]]:
    out: Dict[str, Optional[ast.AST]] = {
        "mesh": None, "in_specs": None, "out_specs": None}
    pos = call.args[skip_args:]
    for name, i in (("mesh", 0), ("in_specs", 1), ("out_specs", 2)):
        if len(pos) > i:
            out[name] = pos[i]
    for k in call.keywords:
        if k.arg in out:
            out[k.arg] = k.value
    return out


def iter_shard_map_sites(ctx: ModuleContext) -> Iterator[ShardMapSite]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                d = dotted(dec.func) or ""
                if d.split(".")[-1] == "partial" and dec.args \
                        and _is_shard_map_ref(ctx, dec.args[0]):
                    kw = _site_kwargs(dec, skip_args=1)
                    yield ShardMapSite(dec.lineno, node, kw["in_specs"],
                                       kw["out_specs"], kw["mesh"],
                                       frozenset({node.name}))
        elif isinstance(node, ast.Call) \
                and _is_shard_map_ref(ctx, node.func):
            body: Optional[ast.AST] = None
            if node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Lambda):
                    body = a0
                elif isinstance(a0, ast.Name):
                    body = ctx._resolve_local_fn(node, a0.id)
            names: Set[str] = set()
            parent = ctx.parent(node)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            kw = _site_kwargs(node, skip_args=1 if node.args else 0)
            yield ShardMapSite(node.lineno, body, kw["in_specs"],
                               kw["out_specs"], kw["mesh"],
                               frozenset(names))


def _merged_axis_env(ctx: ModuleContext
                     ) -> Tuple[Dict[str, int], Dict[str, str], str]:
    """(vocabulary, axis constants, mesh module path) — the in-file
    declarations merged over the package mesh module's, exactly
    ZL022's resolution."""
    vocab, consts = extract_axis_decls(ctx)
    pvocab, pconsts, mesh_path = package_axis_vocabulary(ctx.path)
    if os.path.abspath(ctx.path) == os.path.abspath(mesh_path or ""):
        pvocab, pconsts = {}, {}
    return {**pvocab, **vocab}, {**pconsts, **consts}, mesh_path


def _mesh_vars(ctx: ModuleContext,
               consts: Dict[str, str]) -> Dict[str, FrozenSet[str]]:
    """Variable name → axis set for every in-file ``Mesh(devices,
    (names…))``/``make_mesh(shape, names)`` construction bound to a
    name — the strict per-site binding ZL025 checks against (a
    shard_map over a 2-axis submesh binds only those two names, even
    when the package vocabulary is wider)."""
    out: Dict[str, FrozenSet[str]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        d = dotted(node.value.func) or ""
        if d.split(".")[-1] not in ("Mesh", "make_mesh"):
            continue
        names_arg: Optional[ast.AST] = None
        if len(node.value.args) > 1:
            names_arg = node.value.args[1]
        for k in node.value.keywords:
            if k.arg == "axis_names":
                names_arg = k.value
        if names_arg is None:
            continue
        axes = _fold_axis_names(names_arg, consts, ctx.tree)
        if not axes:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = frozenset(axes)
    return out


# ---------------------------------------------------------------------------
# collective call inspection (shared by the interpreter and the rules)
# ---------------------------------------------------------------------------

def _collective_leaf(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    d = dotted(node.func)
    if not d:
        return None
    parts = d.split(".")
    if parts[-1] in _COLLECTIVES and "lax" in parts:
        return parts[-1]
    return None


def _collective_axis_elts(call: ast.Call, leaf: str) -> List[ast.AST]:
    """The axis-name argument's element expressions (tuple axes yield
    several); ``[]`` when the call has no axis argument."""
    pos = _COLLECTIVES[leaf]
    axis_arg: Optional[ast.AST] = None
    if len(call.args) > pos:
        axis_arg = call.args[pos]
    for k in call.keywords:
        if k.arg == "axis_name":
            axis_arg = k.value
    if axis_arg is None:
        return []
    if isinstance(axis_arg, (ast.Tuple, ast.List)):
        return list(axis_arg.elts)
    return [axis_arg]


def _collective_axes(call: ast.Call, leaf: str,
                     consts: Dict[str, str]
                     ) -> Tuple[List[str], bool]:
    """(resolved axis names, fully_resolved). A parameter-passed axis
    (ring attention's ``axis_name``) resolves nothing and is reported
    unresolved, not guessed."""
    elts = _collective_axis_elts(call, leaf)
    axes: List[str] = []
    ok = True
    for e in elts:
        ax = _resolve_axis_token(e, consts)
        if ax is None:
            # one level through a function-local alias
            # (`axis = mesh_lib.SEQ_AXIS`) is NOT attempted: locals may
            # rebind; consts are module-level truths
            ok = False
        else:
            axes.append(ax)
    if not elts:
        ok = False
    return axes, ok


@dataclasses.dataclass
class CollectiveSite:
    """One collective call site, for the catalog reconciliation."""
    name: str
    axes: Tuple[str, ...]     # resolved axis names; () = unresolvable
    path: str
    line: int


def iter_collective_sites(ctx: ModuleContext) -> Iterator[CollectiveSite]:
    _, consts, _ = _merged_axis_env(ctx)
    for node in ast.walk(ctx.tree):
        leaf = _collective_leaf(node)
        if leaf is None:
            continue
        axes, _ = _collective_axes(node, leaf, consts)
        yield CollectiveSite(leaf, tuple(axes), ctx.path, node.lineno)


# ---------------------------------------------------------------------------
# the abstract interpreter over shard_map bodies
# ---------------------------------------------------------------------------

#: literal constructors — identical content on every rank
_REPLICATED_CTORS = {"zeros", "ones", "full", "empty", "arange", "eye",
                     "array", "asarray", "linspace", "zeros_like",
                     "ones_like", "full_like", "empty_like", "identity"}

#: elementwise / shape ops the walker propagates a joined state through
_ELEMENTWISE = {"where", "select", "add", "subtract", "multiply", "divide",
                "true_divide", "maximum", "minimum", "exp", "log", "log2",
                "sqrt", "square", "abs", "negative", "tanh", "sigmoid",
                "clip", "power", "mod", "remainder", "logical_and",
                "logical_or", "logical_not", "equal", "not_equal",
                "greater", "greater_equal", "less", "less_equal", "isnan",
                "isfinite", "nan_to_num", "astype", "stop_gradient"}

#: array-dim reductions/reshapes — mesh distribution unchanged, but the
#: seed-time axis→dim map no longer applies
_DIM_SCRAMBLERS = {"sum", "mean", "max", "min", "prod", "reshape",
                   "transpose", "swapaxes", "squeeze", "expand_dims",
                   "ravel", "flatten", "moveaxis", "broadcast_to",
                   "concatenate", "stack", "split", "take", "cumsum",
                   "argmax", "argmin", "softmax", "logsumexp"}

_DOT_LIKE = {"dot", "matmul", "tensordot", "dot_general", "einsum"}


class SpmdInterp:
    """Straight-line abstract interpreter over one shard_map body —
    the same shape as ``device.Interp``: statements in order (branch
    arms applied last-writer-wins), one level of local-helper
    resolution, everything unprovable degrading to ``unknown``."""

    def __init__(self, ctx: ModuleContext, consts: Dict[str, str],
                 depth: int = 0):
        self.ctx = ctx
        self.consts = consts
        self.depth = depth
        self.returns: List[Tuple[ast.AST, DistState]] = []

    # -- entry points -------------------------------------------------
    def run_function(self, fn: ast.AST,
                     seeds: Dict[str, DistState]
                     ) -> Tuple[Dict[str, DistState],
                                List[Tuple[ast.AST, DistState]]]:
        env: Dict[str, DistState] = dict(seeds)
        if isinstance(fn, ast.Lambda):
            self.returns.append((fn.body, self.eval(fn.body, env)))
            return env, self.returns
        self.walk_stmts(fn.body, env)
        return env, self.returns

    # -- statements ---------------------------------------------------
    def walk_stmts(self, stmts: Sequence[ast.stmt],
                   env: Dict[str, DistState]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                val = self.eval(stmt.value, env)
                for t in stmt.targets:
                    self._bind_target(t, val, env)
            elif isinstance(stmt, ast.AugAssign) \
                    and isinstance(stmt.target, ast.Name):
                old = env.get(stmt.target.id, DistState.unknown())
                env[stmt.target.id] = join(old,
                                           self.eval(stmt.value, env))
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.value is not None:
                env[stmt.target.id] = self.eval(stmt.value, env)
            elif isinstance(stmt, ast.Return):
                state = self.eval(stmt.value, env) \
                    if stmt.value is not None else DistState.replicated()
                self.returns.append((stmt, state))
            elif isinstance(stmt, ast.If):
                self.walk_stmts(stmt.body, env)
                self.walk_stmts(stmt.orelse, env)
            elif isinstance(stmt, (ast.For, ast.While)):
                if isinstance(stmt, ast.For) \
                        and isinstance(stmt.target, ast.Name):
                    env[stmt.target.id] = DistState.unknown()
                self.walk_stmts(stmt.body, env)
                self.walk_stmts(stmt.orelse, env)
            elif isinstance(stmt, ast.With):
                self.walk_stmts(stmt.body, env)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef, ast.Expr, ast.Pass,
                                   ast.Import, ast.ImportFrom)):
                continue
            elif isinstance(stmt, ast.Try):
                self.walk_stmts(stmt.body, env)
                for h in stmt.handlers:
                    self.walk_stmts(h.body, env)
                self.walk_stmts(stmt.finalbody, env)
            # raise/assert/del/global: no value flow tracked

    def _bind_target(self, target: ast.AST, val: DistState,
                     env: Dict[str, DistState]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = val
        elif isinstance(target, ast.Tuple):
            elts = val.elts
            for i, e in enumerate(target.elts):
                if not isinstance(e, ast.Name):
                    continue
                if elts is not None and i < len(elts):
                    env[e.id] = elts[i]
                elif val.known and val.is_replicated:
                    env[e.id] = DistState.replicated()
                else:
                    env[e.id] = DistState.unknown()

    # -- expressions --------------------------------------------------
    def eval(self, node: ast.AST, env: Dict[str, DistState]) -> DistState:
        if isinstance(node, ast.Constant):
            return DistState.replicated()
        if isinstance(node, ast.Name):
            return env.get(node.id, DistState.unknown())
        if isinstance(node, (ast.Tuple, ast.List)):
            elts = tuple(self.eval(e, env) for e in node.elts)
            return DistState(
                sharded=frozenset().union(*(e.sharded for e in elts))
                if elts else _EMPTY,
                partial=frozenset().union(*(e.partial for e in elts))
                if elts else _EMPTY,
                known=all(e.known for e in elts), elts=elts)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.BinOp):
            left = self.eval(node.left, env)
            right = self.eval(node.right, env)
            if isinstance(node.op, ast.MatMult):
                return dot_transfer(left, right)
            return join(left, right)
        if isinstance(node, ast.BoolOp):
            return join_all([self.eval(v, env) for v in node.values])
        if isinstance(node, ast.Compare):
            return join_all([self.eval(node.left, env)]
                            + [self.eval(c, env)
                               for c in node.comparators]).drop_dims()
        if isinstance(node, ast.IfExp):
            return join(self.eval(node.body, env),
                        self.eval(node.orelse, env))
        if isinstance(node, ast.Subscript):
            return self.eval(node.value, env).drop_dims()
        if isinstance(node, ast.Attribute):
            # `x.T`, `x.dtype`, `x.shape` — follow the receiver; shapes
            # are replicated in a manual region (same block everywhere)
            if node.attr in ("shape", "dtype", "ndim", "size"):
                return DistState.replicated()
            base = self.eval(node.value, env)
            return base if base.known else DistState.unknown()
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        return DistState.unknown()

    def _eval_call(self, call: ast.Call,
                   env: Dict[str, DistState]) -> DistState:
        leaf = _collective_leaf(call)
        if leaf is not None:
            return self._collective_transfer(call, leaf, env)
        d = dotted(call.func) or ""
        parts = d.split(".")
        name = parts[-1] if parts else ""
        # method call on a TRACKED value: `x.astype(...)`,
        # `x.reshape(...)` — only when the receiver is a name bound in
        # this environment, so a module-attribute call (`jnp.where`)
        # falls through to the function branches instead of evaluating
        # the module alias itself (always unknown)
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id in env and name in (
                _ELEMENTWISE | _DIM_SCRAMBLERS):
            recv = env[call.func.value.id]
            args = join_all([recv] + [self.eval(a, env)
                                      for a in call.args])
            return args if name in _ELEMENTWISE else args.drop_dims()
        if name in _REPLICATED_CTORS:
            return DistState.replicated()
        if name in _DOT_LIKE:
            operands = [a for a in call.args
                        if not (isinstance(a, ast.Constant)
                                and isinstance(a.value, str))]
            states = [self.eval(a, env) for a in operands]
            if len(states) >= 2:
                out = states[0]
                for s in states[1:]:
                    out = dot_transfer(out, s)
                return out
            return join_all(states).drop_dims() if states \
                else DistState.unknown()
        if name in _ELEMENTWISE:
            states = [self.eval(a, env) for a in call.args]
            return join_all(states) if states else DistState.unknown()
        if name in _DIM_SCRAMBLERS:
            states = [self.eval(a, env) for a in call.args]
            return (join_all(states) if states
                    else DistState.unknown()).drop_dims()
        # one-level local helper: bind arg states, walk, join returns —
        # the helper-call carry (psum inside a helper still clears)
        if self.depth < 1 and isinstance(call.func, ast.Name):
            fn = self.ctx._resolve_local_fn(call, call.func.id)
            if fn is not None and isinstance(fn, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef)):
                return self._call_helper(fn, call, env)
        return DistState.unknown()

    def _call_helper(self, fn: ast.AST, call: ast.Call,
                     env: Dict[str, DistState]) -> DistState:
        a = fn.args
        params = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        if a.vararg or a.kwarg:
            return DistState.unknown()
        seeds: Dict[str, DistState] = {p: DistState.unknown()
                                       for p in params}
        for p in params[len(params) - len(a.defaults):]:
            seeds[p] = DistState.replicated()   # literal defaults
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                return DistState.unknown()
            if i < len(params):
                seeds[params[i]] = self.eval(arg, env)
        for k in call.keywords:
            if k.arg in seeds:
                seeds[k.arg] = self.eval(k.value, env)
        sub = SpmdInterp(self.ctx, self.consts, depth=self.depth + 1)
        _, rets = sub.run_function(fn, seeds)
        if not rets:
            return DistState.unknown()
        return join_all([s for _, s in rets])

    def _collective_transfer(self, call: ast.Call, leaf: str,
                             env: Dict[str, DistState]) -> DistState:
        axes, resolved = _collective_axes(call, leaf, self.consts)
        if leaf == "axis_size":
            return DistState.replicated()
        if leaf == "axis_index":
            if resolved and axes:
                return DistState.sharded_over(axes)
            return DistState.unknown()
        operand = (self.eval(call.args[0], env) if call.args
                   else DistState.unknown())
        if not resolved:
            return DistState.unknown()
        if leaf in ("psum", "pmean", "pmax", "pmin"):
            return operand.reduce_over(axes)
        if leaf == "psum_scatter":
            return operand.scatter_over(axes)
        if leaf == "all_gather":
            return operand.gather_over(axes)
        if leaf in ("ppermute", "pbroadcast", "pshuffle", "all_to_all"):
            return operand.drop_dims()
        return DistState.unknown()


def interp_source_fn(source: str, fn_name: str,
                     seeds: Dict[str, DistState],
                     path: str = "<spmd>"
                     ) -> Tuple[Dict[str, DistState],
                                List[Tuple[ast.AST, DistState]]]:
    """Test/exploration helper: abstract-interpret one module-level
    function of ``source`` with the given parameter seeds; returns the
    final environment and the (node, state) return list. No fixture
    package or mesh module required — the lattice unit tests drive the
    transfer functions through this."""
    ctx = ModuleContext(path, source)
    consts = _merged_axis_env(ctx)[1]
    fn = None
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fn_name:
            fn = node
            break
    if fn is None:
        raise ValueError(f"no function {fn_name!r} in source")
    return SpmdInterp(ctx, consts).run_function(fn, seeds)


def _seed_env(body: ast.AST, ins: Optional[SpecList]
              ) -> Dict[str, DistState]:
    """Parameter seeds from a folded ``in_specs``: spec axes become the
    sharded set (with their dim positions); anything past the proven
    prefix — or under an unfoldable spec — is unknown."""
    if isinstance(body, ast.Lambda):
        a = body.args
    else:
        a = body.args
    params = [p.arg for p in list(a.posonlyargs) + list(a.args)]
    env: Dict[str, DistState] = {}
    for i, p in enumerate(params):
        spec: Optional[SpecInfo] = None
        if ins is not None:
            if ins.single:
                spec = ins.specs[0]
            elif i < len(ins.specs):
                spec = ins.specs[i]
            elif ins.complete:
                spec = None
        if spec is None:
            env[p] = DistState.unknown()
        else:
            env[p] = DistState.sharded_over(spec.axes(),
                                            spec.dim_index())
    if a.vararg:
        env[a.vararg.arg] = DistState.unknown()
    for p in a.kwonlyargs:
        env[p.arg] = DistState.unknown()
    return env


# ---------------------------------------------------------------------------
# ZL025 — collective axis validity (+ the catalog project half)
# ---------------------------------------------------------------------------

@register
class CollectiveAxisBinding(Rule):
    """**Collective axis validity.** A collective inside a
    ``shard_map`` body must name an axis the enclosing mesh binds: when
    the site's ``mesh=`` argument resolves to an in-file
    ``Mesh(devices, (names…))`` construction, its axis tuple is the
    binding set; otherwise the merged ZL022 vocabulary stands in. A
    ``psum`` over an unbound axis passes every single-chip CPU test and
    raises ``NameError: unbound axis`` only at trace time on a real
    mesh — and ZL022 cannot catch the submesh case, where the axis IS
    in the package vocabulary but the mesh under this shard_map does
    not carry it. Parameter-passed axis names (ring attention's
    ``axis_name``) are unresolvable and skipped: precision over
    recall. The project pass adds the collective-catalog
    reconciliation against docs/guides/PARALLELISM.md."""

    id = "ZL025"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        vocab, consts, _ = _merged_axis_env(ctx)
        mesh_vars = _mesh_vars(ctx, consts)
        sev = ERROR if _in_package(ctx.path) else WARNING
        for site in iter_shard_map_sites(ctx):
            if site.body is None:
                continue
            bound: Optional[FrozenSet[str]] = None
            if isinstance(site.mesh_node, ast.Name):
                bound = mesh_vars.get(site.mesh_node.id)
            if bound is None:
                bound = frozenset(vocab) or None
            if bound is None:
                continue
            for call in ast.walk(site.body):
                leaf = _collective_leaf(call)
                if leaf is None:
                    continue
                axes, _ = _collective_axes(call, leaf, consts)
                for ax in axes:
                    if ax not in bound:
                        yield self.finding(
                            ctx, call.lineno,
                            f"{leaf} over axis '{ax}' inside a shard_map "
                            f"whose mesh binds only "
                            f"{sorted(bound)} — an unbound collective "
                            f"axis fails at trace time on a real mesh "
                            f"only", sev)


def parse_collective_catalog(path: str
                             ) -> List[Tuple[str, Tuple[str, ...],
                                             str, int]]:
    """PARALLELISM.md "Collective catalog": rows of ``(collective
    name, documented axes, path, line)``; an axis cell without
    backticked axis names (``caller``/``—``) documents a
    caller-supplied axis and matches any axis."""
    from .contracts import _cell_tokens, md_table_column
    with open(path, encoding="utf-8") as f:
        text = f.read()
    out: List[Tuple[str, Tuple[str, ...], str, int]] = []
    for cell, line, rest in md_table_column(text, path, "collective"):
        names = [t for t in _cell_tokens(cell) if t and " " not in t]
        axis_cell = rest.split(" | ")[0] if rest else ""
        axes = tuple(t for t in _cell_tokens(axis_cell)
                     if t and " " not in t and t == t.lower()
                     and "`" + t + "`" in axis_cell)
        for name in names:
            out.append((name, axes, path, line))
    return out


def _is_collective_module(path: str) -> bool:
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    return "parallel" in parts or "ops" in parts


@register_project
class CollectiveCatalogDrift(ProjectRule):
    """**Collective-catalog reconciliation (code↔PARALLELISM.md).**
    Every collective call site in the package's ``parallel/`` and
    ``ops/`` trees must have a documented row (name + axis semantics)
    in the PARALLELISM.md collective catalog, and every cataloged
    (collective, axis) pair must correspond to a live call site — a
    collective someone deletes must take its documentation with it,
    and a new one must state which axis it rides and why. Sites whose
    axis is caller-supplied (ring attention's ``axis_name`` parameter)
    match any row of that collective. Inert when the scanned tree has
    no such call sites (foreign/fixture packages)."""

    id = "ZL025"
    severity = ERROR

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        from .contracts import _missing_catalog
        sites: List[CollectiveSite] = []
        for ctx in project.modules:
            if not _is_collective_module(ctx.path):
                continue
            sites.extend(iter_collective_sites(ctx))
        if not sites:
            return
        path = project.catalog_path("collectives")
        if path is None:
            yield _missing_catalog(self, project, "collectives")
            return
        rows = parse_collective_catalog(path)
        by_name: Dict[str, List[Tuple[Tuple[str, ...], str, int]]] = {}
        for name, axes, rpath, line in rows:
            by_name.setdefault(name, []).append((axes, rpath, line))
        covered: Set[Tuple[str, Optional[str]]] = set()
        for s in sites:
            doc_rows = by_name.get(s.name, [])
            if s.axes:
                for ax in s.axes:
                    hit = any(ax in axes or not axes
                              for axes, _, _ in doc_rows)
                    if hit:
                        covered.add((s.name, ax))
                        covered.add((s.name, None))
                    else:
                        yield Finding(
                            self.id, ERROR, s.path, s.line,
                            f"collective {s.name} over axis '{ax}' has "
                            f"no row in {os.path.basename(path)}'s "
                            f"collective catalog — document the axis "
                            f"semantics (what the collective does to "
                            f"values on that axis)")
            else:
                if doc_rows:
                    # a caller-supplied axis exercises every documented
                    # axis of its collective
                    for axes, _, _ in doc_rows:
                        covered.add((s.name, None))
                        for ax in axes:
                            covered.add((s.name, ax))
                else:
                    yield Finding(
                        self.id, ERROR, s.path, s.line,
                        f"collective {s.name} (caller-supplied axis) "
                        f"has no row in {os.path.basename(path)}'s "
                        f"collective catalog — add one")
        for name, doc_rows in sorted(by_name.items()):
            for axes, rpath, line in doc_rows:
                if not axes:
                    if (name, None) not in covered:
                        yield Finding(
                            self.id, ERROR, rpath, line,
                            f"collective {name} is cataloged here but "
                            f"no parallel/ or ops/ call site uses it — "
                            f"prune the row or restore the code")
                    continue
                for ax in axes:
                    if (name, ax) not in covered:
                        yield Finding(
                            self.id, ERROR, rpath, line,
                            f"collective {name} over axis '{ax}' is "
                            f"cataloged here but no parallel/ or ops/ "
                            f"call site uses it — prune the axis or "
                            f"restore the code")


# ---------------------------------------------------------------------------
# ZL026 — unreduced-output hazard
# ---------------------------------------------------------------------------

_STACKING_LEAVES = {"stack", "concatenate", "vstack", "hstack", "dstack"}


def _contains(ctx: ModuleContext, fn: ast.AST, node: ast.AST) -> bool:
    """Lexical containment: ``node`` sits anywhere under ``fn``."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        if cur is fn:
            return True
        cur = ctx.parent(cur)
    return False


def _is_stacking_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func) or ""
    parts = d.split(".")
    if parts[-1] in _STACKING_LEAVES:
        return True
    return parts[-1] in ("map", "tree_map") and (
        "tree" in parts or "tree_util" in parts)


def _is_pinned_call(ctx: ModuleContext, node: ast.AST) -> bool:
    """``with_sharding_constraint(...)`` directly, or a local helper
    whose body applies it (the ``_pin_replicated`` idiom)."""
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func) or ""
    if d.split(".")[-1] == "with_sharding_constraint":
        return True
    if isinstance(node.func, ast.Name):
        fn = ctx._resolve_local_fn(node, node.func.id)
        if fn is not None:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) and (
                        dotted(sub.func) or "").split(".")[-1] \
                        == "with_sharding_constraint":
                    return True
    return False


@register
class UnreducedOutputHazard(Rule):
    """**Unreduced-output hazard.** Two provable forms of the PR-14
    gpipe bug class (values leaving/entering a manual region carrying
    unreduced partial sums, which ``check_vma=False`` ships silently):

    1. *Body side*: the abstract interpreter proves a returned value
       carries ``partial_sum(axis)`` (e.g. a row-parallel dot that was
       never ``psum``-ed) while the matching ``out_specs`` entry claims
       replication or sharding on that axis — the blocks get
       concatenated or rank-0-picked instead of summed.
    2. *Caller side*: a shard_map-wrapped function invoked from
       jit-staged code with an operand computed AT TRACE TIME
       (``jnp.stack``/``jax.tree.map``) and not routed through
       ``with_sharding_constraint`` — GSPMD may commit a layout that
       disagrees with ``in_specs`` and the value enters the region
       unreduced (×data-axis-size per stage, the exact bug
       ``parallel/pipeline.py``'s ``_pin_replicated`` now guards).

    Everything unprovable (unresolvable specs, foreign calls, scan
    carries) degrades to ``unknown`` and is never accused."""

    id = "ZL026"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        _, consts, _ = _merged_axis_env(ctx)
        sev = ERROR if _in_package(ctx.path) else WARNING
        sites = list(iter_shard_map_sites(ctx))
        for site in sites:
            yield from self._check_body(ctx, site, consts, sev)
        yield from self._check_callers(ctx, sites, sev)

    # -- prong 1: partial sums escaping through out_specs -------------
    def _check_body(self, ctx: ModuleContext, site: ShardMapSite,
                    consts: Dict[str, str],
                    sev: str) -> Iterator[Finding]:
        if site.body is None:
            return
        ins = fold_specs(ctx, site.in_specs, consts)
        outs = fold_specs(ctx, site.out_specs, consts)
        if outs is None:
            return
        env = _seed_env(site.body, ins)
        _, returns = SpmdInterp(ctx, consts).run_function(site.body, env)
        for node, state in returns:
            yield from self._match_out(ctx, node, state, outs, sev)

    def _match_out(self, ctx: ModuleContext, node: ast.AST,
                   state: DistState, outs: SpecList,
                   sev: str) -> Iterator[Finding]:
        pairs: List[Tuple[DistState, Optional[SpecInfo]]] = []
        if outs.single:
            spec = outs.specs[0]
            if state.elts is not None:
                pairs = [(e, spec) for e in state.elts]
            else:
                pairs = [(state, spec)]
        elif state.elts is not None and outs.complete \
                and len(state.elts) == len(outs.specs):
            pairs = list(zip(state.elts, outs.specs))
        else:
            return
        line = getattr(node, "lineno", 0) or 0
        for st, spec in pairs:
            if spec is None or not st.known:
                continue
            claimed = spec.axes()
            for ax in sorted(st.partial):
                if ax in claimed:
                    yield self.finding(
                        ctx, line,
                        f"shard_map body returns a value still carrying "
                        f"an unreduced partial sum over axis '{ax}' but "
                        f"out_specs shard that axis — the blocks would "
                        f"be laid out side-by-side, not summed; "
                        f"jax.lax.psum_scatter(..., '{ax}') is the "
                        f"matching reduction", sev)
                elif spec.complete:
                    yield self.finding(
                        ctx, line,
                        f"shard_map body returns a value still carrying "
                        f"an unreduced partial sum over axis '{ax}' but "
                        f"out_specs claim replication on it — insert "
                        f"jax.lax.psum(..., '{ax}') before returning; "
                        f"with check_vma=False this ships wrong numbers "
                        f"silently", sev)
            for ax in sorted(st.sharded - st.partial):
                if spec.complete and ax not in claimed:
                    yield self.finding(
                        ctx, line,
                        f"shard_map body returns a device-varying value "
                        f"(sharded over '{ax}') but out_specs claim "
                        f"replication on that axis — ranks disagree and "
                        f"check_vma=False picks one silently; gather or "
                        f"reduce over '{ax}' first", sev)

    # -- prong 2: unpinned trace-time operands entering the region ----
    def _check_callers(self, ctx: ModuleContext,
                       sites: List[ShardMapSite],
                       sev: str) -> Iterator[Finding]:
        wrapped = frozenset().union(*(s.names for s in sites)) \
            if sites else frozenset()
        if not wrapped:
            return
        staged = staged_fns(ctx)
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call) \
                    or not isinstance(call.func, ast.Name) \
                    or call.func.id not in wrapped:
                continue
            if not any(_contains(ctx, fn, call) for fn in staged):
                continue        # eager operands carry committed layouts
            for arg in call.args:
                verdict = self._classify_operand(ctx, arg)
                if verdict is None:
                    continue
                yield self.finding(
                    ctx, call.lineno,
                    f"operand computed inside this jit ({verdict}) "
                    f"enters the shard_map manual region without a "
                    f"committed layout — GSPMD may pick one that "
                    f"disagrees with in_specs and the value arrives "
                    f"UNREDUCED (×axis-size; the gpipe stacked-stage-"
                    f"params bug). Pin it replicated with "
                    f"jax.lax.with_sharding_constraint before the "
                    f"call", sev)

    def _classify_operand(self, ctx: ModuleContext, arg: ast.AST,
                          depth: int = 0) -> Optional[str]:
        """A human-readable producer description when ``arg`` is a
        trace-time stacking intermediate with no layout pin; None when
        pinned or not provably hazardous."""
        if depth > 2:
            return None
        if _is_pinned_call(ctx, arg):
            return None
        if _is_stacking_call(arg):
            return f"{dotted(arg.func)} at line {arg.lineno}"
        if isinstance(arg, ast.Name):
            bound = _single_binding(ctx, arg, arg.id)
            if bound is not None and bound[1] is None:
                return self._classify_operand(ctx, bound[0], depth + 1)
        return None


# ---------------------------------------------------------------------------
# ZL027 — divergent collectives under traced control flow
# ---------------------------------------------------------------------------

@register
class DivergentCollective(Rule):
    """**Divergent collectives under traced control flow.** Collectives
    are rendezvous points: EVERY rank along the axis must reach the
    same collective the same number of times. A collective inside only
    one branch of a ``lax.cond`` (or anywhere inside a
    ``lax.while_loop``, whose traced trip count can differ per rank
    when the predicate is device-varying) means some ranks enter the
    rendezvous and the rest never arrive — an SPMD deadlock that no
    single-chip CPU test can reproduce. ``lax.scan``/``fori_loop``
    bodies are exempt: their trip counts are static, every rank runs
    the identical schedule (the ring/GPipe pattern). A branch that
    does not resolve to a local function is skipped — divergence must
    be provable."""

    id = "ZL027"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        _, consts, _ = _merged_axis_env(ctx)
        sev = ERROR if _in_package(ctx.path) else WARNING
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            parts = d.split(".")
            leaf = parts[-1]
            if "lax" not in parts:
                continue
            if leaf == "while_loop":
                for role, fn_arg in (("cond", node.args[0:1]),
                                     ("body", node.args[1:2])):
                    fn = self._resolve_branch(ctx, node,
                                              fn_arg[0]) if fn_arg \
                        else None
                    if fn is None:
                        continue
                    for call, cleaf, axes in self._collectives_in(
                            ctx, fn, consts):
                        yield self.finding(
                            ctx, call.lineno,
                            f"{cleaf} inside a lax.while_loop {role} — "
                            f"the traced trip count can differ per "
                            f"rank, so ranks that exit earlier never "
                            f"reach the collective: SPMD deadlock. "
                            f"Hoist it out of the loop or use a "
                            f"static-trip lax.scan", sev)
            elif leaf == "cond" and len(node.args) >= 3:
                t = self._resolve_branch(ctx, node, node.args[1])
                f = self._resolve_branch(ctx, node, node.args[2])
                if t is None or f is None:
                    continue
                tcoll = list(self._collectives_in(ctx, t, consts))
                fcoll = list(self._collectives_in(ctx, f, consts))
                tkeys = {(c[1], frozenset(c[2])) for c in tcoll}
                fkeys = {(c[1], frozenset(c[2])) for c in fcoll}
                for branch, other_keys, arm in ((tcoll, fkeys, "true"),
                                                (fcoll, tkeys, "false")):
                    for call, cleaf, axes in branch:
                        if (cleaf, frozenset(axes)) in other_keys:
                            continue
                        yield self.finding(
                            ctx, call.lineno,
                            f"{cleaf} reachable only in the {arm} "
                            f"branch of a lax.cond — ranks whose "
                            f"predicate takes the other branch never "
                            f"reach the collective: SPMD deadlock. "
                            f"Run the collective in both branches (or "
                            f"outside the cond)", sev)

    @staticmethod
    def _resolve_branch(ctx: ModuleContext, call: ast.Call,
                        arg: ast.AST) -> Optional[ast.AST]:
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return ctx._resolve_local_fn(call, arg.id)
        return None

    @staticmethod
    def _collectives_in(ctx: ModuleContext, fn: ast.AST,
                        consts: Dict[str, str]
                        ) -> Iterator[Tuple[ast.Call, str, List[str]]]:
        for sub in ast.walk(fn):
            leaf = _collective_leaf(sub)
            if leaf is None:
                continue
            axes, _ = _collective_axes(sub, leaf, consts)
            yield sub, leaf, axes


# ---------------------------------------------------------------------------
# ZL028 — PartitionSpec hygiene
# ---------------------------------------------------------------------------

@register
class PartitionSpecHygiene(Rule):
    """**PartitionSpec hygiene.** Structural spec facts that are
    provable without a mesh: (a) a mesh axis used twice in one
    ``PartitionSpec`` — jax rejects duplicate axes in a spec at trace
    time, on a multi-chip mesh only; (b) arity at ``shard_map`` sites
    where both sides are proven — an ``in_specs`` tuple whose length
    differs from the body's parameter count, or ``out_specs`` whose
    length differs from the returned tuple's (conditional spec tails
    and ``*args`` bodies are unprovable and skipped). Axis-name
    VOCABULARY membership stays ZL022's job — one rule per fact, one
    suppression per intent."""

    id = "ZL028"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        _, consts, _ = _merged_axis_env(ctx)
        sev = ERROR if _in_package(ctx.path) else WARNING
        for node in ast.walk(ctx.tree):
            if _is_pspec_call(ctx, node):
                spec = _fold_pspec(ctx, node, consts)
                seen: Set[str] = set()
                for d in spec.dims:
                    for ax in d:
                        if ax in seen:
                            yield self.finding(
                                ctx, node.lineno,
                                f"axis '{ax}' appears twice in one "
                                f"PartitionSpec — jax rejects a "
                                f"duplicate mesh axis in a spec at "
                                f"trace time (on a real mesh only)",
                                sev)
                        seen.add(ax)
        for site in iter_shard_map_sites(ctx):
            yield from self._check_arity(ctx, site, consts, sev)

    def _check_arity(self, ctx: ModuleContext, site: ShardMapSite,
                     consts: Dict[str, str],
                     sev: str) -> Iterator[Finding]:
        body = site.body
        if body is None:
            return
        a = body.args
        if a.vararg or a.kwarg or a.kwonlyargs or a.defaults:
            return
        nparams = len(a.posonlyargs) + len(a.args)
        ins = fold_specs(ctx, site.in_specs, consts)
        if ins is not None and ins.complete and not ins.single \
                and len(ins.specs) != nparams:
            yield self.finding(
                ctx, site.line,
                f"shard_map in_specs has {len(ins.specs)} spec(s) but "
                f"the body takes {nparams} parameter(s) — the mismatch "
                f"only fails at trace time", sev)
        outs = fold_specs(ctx, site.out_specs, consts)
        if outs is None or outs.single or not outs.complete:
            return
        ret_lens: Set[int] = set()
        if isinstance(body, ast.Lambda):
            ret_lens.add(len(body.body.elts)
                         if isinstance(body.body, ast.Tuple) else 1)
        else:
            for n in ast.walk(body):
                if isinstance(n, ast.Return) and n.value is not None \
                        and ctx._enclosing_scope(n) is body:
                    ret_lens.add(len(n.value.elts)
                                 if isinstance(n.value, ast.Tuple)
                                 else 1)
        if len(ret_lens) == 1:
            (L,) = ret_lens
            # a 1-element return against N specs is unprovable (the
            # body may return a tuple-valued expression); only a
            # PROVEN tuple literal of the wrong length is accused
            if L > 1 and L != len(outs.specs):
                yield self.finding(
                    ctx, site.line,
                    f"shard_map out_specs has {len(outs.specs)} "
                    f"spec(s) but the body returns a {L}-tuple — the "
                    f"mismatch only fails at trace time", sev)
