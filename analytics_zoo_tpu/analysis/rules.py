"""zoolint per-file rules ZL001–ZL015 — the JAX/TPU hazards that bite
this stack (the whole-project rules ZL016–ZL020 live in ``project.py``/
``contracts.py``; the device-semantics pass ZL021–ZL024 in
``device.py``; the SPMD collective-semantics pass ZL025–ZL028 in
``spmd.py``).

Every rule documents its rationale in the class docstring (surfaced by
``--list-rules`` and docs/guides/STATIC_ANALYSIS.md). Severities:

* ``error``   — gates CI (``tests/test_zoolint.py`` asserts zero). The
  heuristic rules ZL005/ZL008 started warn-only and were promoted once
  the existing findings were triaged (every remaining site carries a
  justified suppression) — see the ROADMAP follow-up.
* ``warning`` — advisory only (ZL007's swallow-pass form outside the
  serving/inference retry paths).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import (ERROR, WARNING, Finding, ModuleContext, Rule, dotted,
                   param_names, register)


def _walk_skipping(root: ast.AST, skip_types=(),
                   skip_nodes=frozenset()) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into the given node types or
    specific node ids (the root itself is always yielded)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, skip_types) or id(child) in skip_nodes:
                continue
            stack.append(child)


_CALLBACK_LEAVES = {"callback", "pure_callback", "io_callback"}


def _callback_hosted_fns(ctx: ModuleContext, fn: ast.AST) -> Set[int]:
    """ids of nested functions/lambdas passed to a host-callback API
    (``jax.debug.callback`` / ``jax.pure_callback`` / ``io_callback``) —
    their bodies run on the HOST at execution time, not at trace, so the
    under-jit effect/sync rules must not flag them."""
    out: Set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if not d or d.rsplit(".", 1)[-1] not in _CALLBACK_LEAVES:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Lambda):
                out.add(id(arg))
            elif isinstance(arg, ast.Name):
                target = ctx._resolve_local_fn(node, arg.id)
                if target is not None:
                    out.add(id(target))
    return out


# ---------------------------------------------------------------------------
# ZL001 — PRNG key reuse
# ---------------------------------------------------------------------------

# jax.random callables that do NOT consume their key: ``fold_in`` derives
# without consuming (the idiomatic per-step schedule used across parallel/
# and the keras engine), and the constructors make fresh keys. ``split``
# is deliberately absent — it both consumes and is checked against earlier
# consumption, and _key_call classifies it before this set is consulted.
_NON_CONSUMING = {"fold_in", "key", "PRNGKey", "wrap_key_data",
                  "key_data", "clone", "key_impl"}


@register
class PRNGKeyReuse(Rule):
    """A ``jax.random`` key passed to a second sampler (or re-``split``)
    without an intervening ``split``/reassignment replays the exact same
    random stream — dropout masks repeat, initializers correlate, and the
    bug is invisible at runtime because every draw still *looks* random.
    Loop bodies are scanned twice so a loop-invariant key consumed each
    iteration is caught as well."""

    id = "ZL001"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for scope in [ctx.tree] + list(ctx.functions()):
            findings: List[Finding] = []
            self._walk(ctx, scope.body, {}, findings)
            yield from findings
        # lambda bodies are their own scope (params are fresh bindings, so
        # they start with an empty consumed-set), but a key consumed twice
        # WITHIN one body is still reuse on every call
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Lambda):
                findings = []
                self._scan_expr(ctx, node.body, {}, findings)
                yield from findings

    # -- statement-ordered dataflow walk ------------------------------------
    def _key_call(self, ctx: ModuleContext,
                  call: ast.Call) -> Optional[Tuple[str, str]]:
        """(kind, keyname) for a ``jax.random.X(key, ...)`` call with a
        simple Name key, where kind is 'sampler' | 'split' | 'other'."""
        d = dotted(call.func)
        if not d or "." not in d:
            return None
        prefix, leaf = d.rsplit(".", 1)
        if prefix not in ctx.aliases["jax.random"]:
            return None
        # the key rides as the first positional OR the `key=` keyword —
        # `key` is positional-or-keyword in every jax.random sampler
        key_node: Optional[ast.AST] = None
        if call.args:
            key_node = call.args[0]
        else:
            for kw in call.keywords:
                if kw.arg == "key":
                    key_node = kw.value
                    break
        if not isinstance(key_node, ast.Name):
            return None
        name = key_node.id
        if leaf == "split":
            return "split", name
        if leaf in _NON_CONSUMING:
            return "other", name
        return "sampler", name

    _COMPS = (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)

    def _scan_expr(self, ctx, node, consumed: Dict[str, int],
                   findings: List[Finding],
                   comp_bound: frozenset = frozenset()) -> None:
        stack = [(node, comp_bound)]    # (node, names bound per-iteration)
        while stack:
            sub, comp_bound = stack.pop()
            if isinstance(sub, ast.Lambda):
                continue    # own scope: params shadow (visited separately)
            if isinstance(sub, ast.IfExp):
                # mutually-exclusive arms: at most one consumes at
                # runtime — branch the consumed-set like the
                # statement-level ast.If handling in _walk
                self._scan_expr(ctx, sub.test, consumed, findings,
                                comp_bound)
                branches = []
                for arm in (sub.body, sub.orelse):
                    c = dict(consumed)
                    self._scan_expr(ctx, arm, c, findings, comp_bound)
                    branches.append(c)
                for c in branches:
                    consumed.update(c)
                continue
            if isinstance(sub, ast.BoolOp):
                # short-circuit is sequential-PREFIX, not exclusive arms:
                # operand i evaluates only after operands 0..i-1 already
                # did (and consumed) — accumulate in order so reuse
                # across `and`/`or` operands is caught
                for v in sub.values:
                    self._scan_expr(ctx, v, consumed, findings, comp_bound)
                continue
            if isinstance(sub, self._COMPS):
                # a comprehension body runs once per element: any key it
                # consumes that is NOT the comprehension's own loop
                # variable is loop-invariant reuse
                bound = set(comp_bound)
                for gen in sub.generators:
                    for t in ast.walk(gen.target):
                        if isinstance(t, ast.Name):
                            bound.add(t.id)
                bound_f = frozenset(bound)
                # the FIRST generator's iterable evaluates once in the
                # enclosing scope — `for k in jax.random.split(rng, n)`
                # is the idiomatic fix, not per-element reuse
                for i, gen in enumerate(sub.generators):
                    stack.append((gen.iter,
                                  comp_bound if i == 0 else bound_f))
                    for cond in gen.ifs:
                        stack.append((cond, bound_f))
                if isinstance(sub, ast.DictComp):
                    stack.append((sub.key, bound_f))
                    stack.append((sub.value, bound_f))
                else:
                    stack.append((sub.elt, bound_f))
                continue
            if isinstance(sub, ast.Call):
                kc = self._key_call(ctx, sub)
                if kc is not None and kc[0] != "other":
                    kind, name = kc
                    if comp_bound and name not in comp_bound:
                        findings.append(self.finding(
                            ctx, sub.lineno,
                            f"PRNG key `{name}` is consumed once per "
                            f"comprehension element — every draw is "
                            f"identical; fold_in/split per element "
                            f"instead"))
                    elif name in consumed:
                        verb = ("re-split" if kind == "split"
                                else "passed to a sampler")
                        findings.append(self.finding(
                            ctx, sub.lineno,
                            f"PRNG key `{name}` already consumed on line "
                            f"{consumed[name]} is {verb} again — derive a "
                            f"fresh key with jax.random.split/fold_in"))
                    elif not comp_bound:
                        consumed[name] = sub.lineno
            elif isinstance(sub, ast.NamedExpr) and \
                    isinstance(sub.target, ast.Name):
                consumed.pop(sub.target.id, None)
            # push reversed so the LIFO pop visits children in SOURCE
            # order — the "already consumed on line N" message must cite
            # the earlier call and anchor the later one, not vice versa
            for child in reversed(list(ast.iter_child_nodes(sub))):
                stack.append((child, comp_bound))

    @staticmethod
    def _bound_names(target) -> Iterator[str]:
        """Names in BINDING position only — ``d[k] = v`` / ``obj.k = v``
        assign THROUGH ``k``/``obj`` without rebinding them, so they must
        not clear a key's consumed state."""
        stack = [target]
        while stack:
            t = stack.pop()
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)

    @classmethod
    def _terminates(cls, stmts) -> bool:
        """Whether a statement list never falls through (its last statement
        unconditionally leaves the block). Such a branch's consumed-set
        must not merge into the fall-through state — the idiomatic
        early-return `if fast: return jax.random.normal(k, ...)` does not
        consume `k` on the path that reaches the next sampler."""
        if not stmts:
            return False
        last = stmts[-1]
        if isinstance(last, (ast.Return, ast.Raise, ast.Break,
                             ast.Continue)):
            return True
        if isinstance(last, ast.If):
            return cls._terminates(last.body) and cls._terminates(last.orelse)
        if isinstance(last, ast.Try):
            return (cls._terminates(last.finalbody)
                    or (cls._terminates(last.orelse if last.orelse
                                        else last.body)
                        and all(cls._terminates(h.body)
                                for h in last.handlers)))
        if isinstance(last, (ast.With, ast.AsyncWith)):
            return cls._terminates(last.body)
        return False

    def _walk(self, ctx, stmts, consumed: Dict[str, int],
              findings: List[Finding]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue    # separate scope, visited on its own
            if isinstance(st, ast.If):
                self._scan_expr(ctx, st.test, consumed, findings)
                c1, c2 = dict(consumed), dict(consumed)
                self._walk(ctx, st.body, c1, findings)
                self._walk(ctx, st.orelse, c2, findings)
                consumed.clear()
                if not self._terminates(st.body):
                    consumed.update(c1)
                if not self._terminates(st.orelse):
                    consumed.update(c2)
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                head = st.iter if isinstance(st, (ast.For, ast.AsyncFor)) \
                    else st.test
                self._scan_expr(ctx, head, consumed, findings)
                # two passes over the body: the second catches a key that
                # is consumed each iteration but only rebound outside. A
                # body that never falls through runs at most one iteration
                # (`for ...: return jax.random.normal(k, ...)` is not
                # reuse), so the rescan is skipped
                for _ in range(2):
                    if isinstance(st, (ast.For, ast.AsyncFor)):
                        for n in self._bound_names(st.target):
                            consumed.pop(n, None)
                    self._walk(ctx, st.body, consumed, findings)
                    if self._terminates(st.body):
                        break
                self._walk(ctx, st.orelse, consumed, findings)
            elif isinstance(st, (ast.Try,)):
                # a handler runs only when the body failed — possibly
                # before it consumed anything — so each handler branches
                # from the PRE-body state (like ast.If arms); orelse runs
                # only after the full body, finalbody always
                pre = dict(consumed)
                self._walk(ctx, st.body, consumed, findings)
                branches = []
                for h in st.handlers:
                    c = dict(pre)
                    self._walk(ctx, h.body, c, findings)
                    branches.append(c)
                self._walk(ctx, st.orelse, consumed, findings)
                for h, c in zip(st.handlers, branches):
                    if not self._terminates(h.body):
                        consumed.update(c)
                self._walk(ctx, st.finalbody, consumed, findings)
            elif isinstance(st, ast.Match):
                # case arms are mutually exclusive — branch like ast.If
                self._scan_expr(ctx, st.subject, consumed, findings)
                branches = []
                for case in st.cases:
                    c = dict(consumed)
                    if case.guard is not None:
                        self._scan_expr(ctx, case.guard, c, findings)
                    self._walk(ctx, case.body, c, findings)
                    branches.append((case, c))
                for case, c in branches:
                    if not self._terminates(case.body):
                        consumed.update(c)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._scan_expr(ctx, item.context_expr, consumed,
                                    findings)
                self._walk(ctx, st.body, consumed, findings)
            elif isinstance(st, ast.Assign):
                self._scan_expr(ctx, st.value, consumed, findings)
                for t in st.targets:
                    for n in self._bound_names(t):
                        consumed.pop(n, None)
            elif isinstance(st, ast.AugAssign):
                self._scan_expr(ctx, st.value, consumed, findings)
                for n in self._bound_names(st.target):
                    consumed.pop(n, None)
            elif isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    self._scan_expr(ctx, st.value, consumed, findings)
                for n in self._bound_names(st.target):
                    consumed.pop(n, None)
            elif isinstance(st, ast.Delete):
                for t in st.targets:
                    for n in self._bound_names(t):
                        consumed.pop(n, None)
            else:
                self._scan_expr(ctx, st, consumed, findings)


# ---------------------------------------------------------------------------
# ZL002 — host side effects under jit
# ---------------------------------------------------------------------------

_BARE_EFFECTS = {"print", "input", "breakpoint", "open", "exec", "eval"}
_TIME_EFFECTS = {"time", "perf_counter", "perf_counter_ns", "monotonic",
                 "monotonic_ns", "sleep", "process_time", "time_ns"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_LOG_OBJECTS = {"log", "logger", "logging"}


@register
class HostEffectInJit(Rule):
    """``print``/``time.time``/logging inside a jitted function executes
    once at TRACE time and never again — the timestamp is the compile
    time, the log line fires on recompiles only, and under donation the
    printed value may alias a freed buffer. Use ``jax.debug.print`` /
    ``jax.debug.callback`` for traced-value output."""

    id = "ZL002"
    severity = ERROR

    def _banned(self, ctx: ModuleContext,
                d: Optional[str]) -> Optional[str]:
        if not d:
            return None
        if d in _BARE_EFFECTS:
            return f"`{d}`"
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            if ctx.is_call_to(d, "time", _TIME_EFFECTS):
                return f"`{d}`"
            if leaf in _LOG_METHODS and (
                    prefix.split(".")[0] in _LOG_OBJECTS
                    or prefix in ctx.aliases["logging"]):
                return f"`{d}`"
        else:
            # from-imports: `from time import perf_counter [as pc]`
            if ctx.from_imported("time").get(d) in _TIME_EFFECTS:
                return f"`{d}` (time.*)"
            if ctx.from_imported("logging").get(d) in _LOG_METHODS:
                return f"`{d}` (logging.*)"
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for info in ctx.jitted.values():
            hosted = _callback_hosted_fns(ctx, info.fn)
            for node in _walk_skipping(info.fn, skip_nodes=hosted):
                if not isinstance(node, ast.Call):
                    continue
                what = self._banned(ctx, dotted(node.func))
                if what:
                    yield self.finding(
                        ctx, node.lineno,
                        f"host side effect {what} inside jitted "
                        f"`{getattr(info.fn, 'name', '<fn>')}` runs at "
                        f"trace time only — use jax.debug.print/callback")


# ---------------------------------------------------------------------------
# ZL003 — hidden host sync in a traced body
# ---------------------------------------------------------------------------

_SYNC_METHODS = {"item", "block_until_ready", "tolist"}


@register
class HostSyncInStep(Rule):
    """``.item()`` / ``np.asarray`` / ``jax.device_get`` /
    ``block_until_ready`` inside a jitted function or a ``lax.scan``-family
    body forces the traced value to a concrete host value — at best a
    ``TracerError``, at worst (on module constants) a silent
    device→host→device round-trip baked into every step."""

    id = "ZL003"
    severity = ERROR

    def _offense(self, ctx: ModuleContext,
                 node: ast.Call) -> Optional[str]:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_METHODS:
            return f"`.{node.func.attr}()`"
        d = dotted(node.func)
        if not d:
            return None
        # import-resolved like ZL002/ZL006: a local helper that happens to
        # be NAMED device_get must not produce an error-severity finding
        mods, froms = ctx.jax_names
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            if leaf == "device_get" and prefix.split(".", 1)[0] in mods:
                return f"`{d}`"
        elif froms.get(d) == "device_get":
            return f"`{d}`"
        leaf = ctx.is_call_to(d, "numpy", ("asarray", "array", "copy"))
        if leaf:
            return f"`{d}`"
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        bodies = [(info.fn, getattr(info.fn, "name", "<fn>"))
                  for info in ctx.jitted.values()]
        bodies += [(fn, getattr(fn, "name", "<lambda>"))
                   for fn in ctx.scan_bodies]
        seen: Set[int] = set()
        for fn, name in bodies:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            hosted = _callback_hosted_fns(ctx, fn)
            for node in _walk_skipping(fn, skip_nodes=hosted):
                if isinstance(node, ast.Call):
                    what = self._offense(ctx, node)
                    if what:
                        yield self.finding(
                            ctx, node.lineno,
                            f"{what} in traced `{name}` forces a host "
                            f"sync/concretization — keep the value on "
                            f"device (jnp.*) or move the readback out of "
                            f"the traced body")


# ---------------------------------------------------------------------------
# ZL004 — Python control flow on a traced value
# ---------------------------------------------------------------------------

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                 "weak_type"}
_SAFE_FUNCS = {"len", "isinstance", "getattr", "hasattr", "callable",
               "type", "id"}


def _traced_name_in_expr(ctx: ModuleContext, test: ast.AST,
                         traced: Set[str]) -> Optional[str]:
    """First traced NAME an expression would concretize — the shared
    heuristic behind ZL004 (if/while tests) and ZL013 (assert tests):
    static-metadata attributes (``x.shape``), metadata builtins
    (``len``/``isinstance``/...), identity and ``is None`` comparisons
    don't concretize and are not flagged."""
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in traced):
            continue
        par = ctx.parent(node)
        if isinstance(par, ast.Attribute) \
                and par.attr in _STATIC_ATTRS:
            continue
        if isinstance(par, ast.Call):
            if node is par.func:
                continue
            if dotted(par.func) in _SAFE_FUNCS:
                continue
        if isinstance(par, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in par.ops):
                continue
            operands = [par.left] + list(par.comparators)
            if any(isinstance(o, ast.Constant) and o.value is None
                   for o in operands):
                continue
        return node.id
    return None


def _traced_params(info) -> Set[str]:
    """Traced (non-static) parameter names of a jitted function — the
    shared set construction behind ZL004 (branch tests) and ZL013
    (assert tests): every positional and kwonly param minus the
    ``static_argnames``, minus ``self``/``cls``. One definition so the
    two rules can never drift on which names count as traced."""
    fn = info.fn
    traced = {n for n in param_names(fn)
              if n not in info.static_names} - {"self", "cls"}
    traced.update(kw.arg for kw in fn.args.kwonlyargs
                  if kw.arg not in info.static_names)
    return traced


@register
class TracedBranch(Rule):
    """A Python ``if``/``while`` on a traced argument concretizes it at
    trace time — ``TracerBoolConversionError`` at best, or (when jit
    falls back to recompiling per value) a silent compile per distinct
    input. Branch on static metadata (``x.shape``, ``x.ndim``), mark the
    argument static, or use ``lax.cond``/``lax.select``/``jnp.where``."""

    id = "ZL004"
    severity = ERROR

    def _test_traced_name(self, ctx: ModuleContext, test: ast.AST,
                          traced: Set[str]) -> Optional[str]:
        return _traced_name_in_expr(ctx, test, traced)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for info in ctx.jitted.values():
            fn = info.fn
            traced = _traced_params(info)
            if not traced:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if ctx.in_nested_scope(node, fn):   # own scope: shadows
                    continue
                name = self._test_traced_name(ctx, node.test, traced)
                if name:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    yield self.finding(
                        ctx, node.lineno,
                        f"Python `{kind}` on traced argument `{name}` of "
                        f"jitted `{getattr(fn, 'name', '<fn>')}` — use "
                        f"lax.cond/jnp.where, branch on static metadata, "
                        f"or mark the argument static")


# ---------------------------------------------------------------------------
# ZL005 — per-element device work built in a Python loop (warn)
# ---------------------------------------------------------------------------

_BUILD_SINKS = {"stack", "concatenate", "array", "asarray", "vstack",
                "hstack"}


@register
class LoopBuiltArray(Rule):
    """A Python loop appending per-element ``jnp`` results that are later
    ``jnp.stack``-ed dispatches one device op (and potentially one
    compile) per element; ``vmap`` or a batched op does it in one fused
    kernel. Heuristic — loops over layers/pytrees of distinct shapes are
    legitimate and carry a justified suppression (cf. ``layers/gpipe.py``);
    error severity since the package-wide triage (ROADMAP follow-up)."""

    id = "ZL005"
    severity = ERROR

    def _jnp_call_inside(self, ctx: ModuleContext, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = dotted(sub.func)
                if d and "." in d:
                    prefix = d.rsplit(".", 1)[0]
                    if prefix in ctx.aliases["jax.numpy"]:
                        return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # one lexical scope at a time: appended-list names and stack-sink
        # names must come from the SAME function (or the module top level)
        # — a bare-name match across unrelated scopes is meaningless, and
        # the module pass must not re-walk every function body
        nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        for fn in list(ctx.functions()) + [ctx.tree]:
            scope = [n for st in fn.body if not isinstance(st, nested)
                     for n in _walk_skipping(st, skip_types=nested)]
            loops: List[Tuple[ast.For, Set[str]]] = []
            for node in scope:
                if not isinstance(node, ast.For):
                    continue
                appended: Set[str] = set()
                for sub in _walk_skipping(node, skip_types=nested):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "append"
                            and isinstance(sub.func.value, ast.Name)
                            and sub.args
                            and self._jnp_call_inside(ctx, sub.args[0])):
                        appended.add(sub.func.value.id)
                if appended:
                    loops.append((node, appended))
            if not loops:
                continue
            sinks: Set[str] = set()
            for node in scope:
                if isinstance(node, ast.Call):
                    d = dotted(node.func)
                    if d and "." in d and \
                            d.rsplit(".", 1)[-1] in _BUILD_SINKS and \
                            d.rsplit(".", 1)[0] in ctx.aliases["jax.numpy"]:
                        for arg in node.args:
                            for sub in ast.walk(arg):
                                if isinstance(sub, ast.Name):
                                    sinks.add(sub.id)
            for loop, appended in loops:
                hit = appended & sinks
                if hit:
                    yield self.finding(
                        ctx, loop.lineno,
                        f"list `{sorted(hit)[0]}` built from jnp results "
                        f"in a Python loop then stacked — consider "
                        f"jax.vmap or a batched op (one dispatch instead "
                        f"of one per element)")


# ---------------------------------------------------------------------------
# ZL006 — import-time device/mesh init & mutable defaults
# ---------------------------------------------------------------------------

_DEVICE_LEAVES = {"devices", "local_devices", "device_count",
                  "local_device_count", "process_count", "process_index"}
_MESH_LEAVES = {"Mesh", "create_mesh", "make_mesh", "create_device_mesh"}
_MUTABLE_CTORS = {"list", "dict", "set", "bytearray",
                  "collections.defaultdict", "collections.OrderedDict"}


@register
class ImportTimeHazard(Rule):
    """Module-level ``jax.devices()``/``Mesh`` construction runs at import
    — before ``jax.distributed.initialize`` on multi-host, it pins a
    single-process backend and every later mesh is wrong (see
    ``parallel/mesh.py``'s lazy ``global_mesh()``). Mutable default
    arguments are the classic shared-state bug: one instance mutates,
    every later call sees it."""

    id = "ZL006"
    severity = ERROR

    def _device_call(self, node: ast.Call,
                     ctx: ModuleContext) -> Optional[str]:
        """The dotted name iff this call resolves to jax device/mesh API.
        Import-resolved (like the jit detection in core.py): a bare name
        must be from-imported off a jax module, a dotted one must hang
        off a local jax-module alias — so ``trimesh.Mesh(...)`` or a
        local ``make_mesh()`` never produces an error-severity finding,
        and ``import jax as j; j.devices()`` does."""
        d = dotted(node.func)
        if not d:
            return None
        mods, froms = ctx.jax_names
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            if prefix.split(".", 1)[0] not in mods:
                return None
        else:
            leaf = froms.get(d)
            if leaf is None:
                return None
        if leaf in _DEVICE_LEAVES or leaf in _MESH_LEAVES:
            return d
        return None

    @staticmethod
    def _not_import_time_guard(test: ast.AST) -> bool:
        """``if __name__ == "__main__":`` bodies run as a script entry
        point, not when the module is imported; ``if TYPE_CHECKING:``
        bodies never run at all — neither is an import-time hazard."""
        if dotted(test) in ("TYPE_CHECKING", "typing.TYPE_CHECKING"):
            return True
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.ops[0], ast.Eq):
            sides = [test.left] + list(test.comparators)
            return ("__name__" in {dotted(s) for s in sides}
                    and any(isinstance(s, ast.Constant)
                            and s.value == "__main__" for s in sides))
        return False

    def _walk_import_time(self, stmts) -> Iterator[ast.AST]:
        """Expressions evaluated at import: module/class bodies (through
        if/try/with/loops — including their head expressions: the ``if``
        test, the ``for`` iterable, the ``with`` context managers) plus
        def-statement default args and decorators, and class decorators/
        bases/keywords — but not function bodies, main-guard bodies, or
        ``TYPE_CHECKING`` blocks."""
        for st in stmts:
            if isinstance(st, ast.If) and \
                    self._not_import_time_guard(st.test):
                # the else-branch of a guard still runs at import
                yield from self._walk_import_time(st.orelse)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from st.decorator_list
                for default in (list(st.args.defaults)
                                + [d for d in st.args.kw_defaults if d]):
                    yield default
                continue
            if isinstance(st, ast.ClassDef):
                yield from st.decorator_list
                yield from st.bases
                for kw in st.keywords:
                    yield kw.value
                yield from self._walk_import_time(st.body)
                continue
            if isinstance(st, (ast.If, ast.While)):
                yield st.test
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                yield st.iter
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    yield item.context_expr
            for attr in ("body", "orelse", "finalbody"):
                if hasattr(st, attr):
                    yield from self._walk_import_time(getattr(st, attr))
            if hasattr(st, "handlers"):
                for h in st.handlers:
                    yield from self._walk_import_time(h.body)
            if not hasattr(st, "body"):
                yield st

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.functions():
            for default in (list(fn.args.defaults)
                            + [d for d in fn.args.kw_defaults if d]):
                bad = isinstance(default, (ast.List, ast.Dict, ast.Set,
                                           ast.ListComp, ast.DictComp,
                                           ast.SetComp))
                if not bad and isinstance(default, ast.Call):
                    bad = dotted(default.func) in _MUTABLE_CTORS
                if bad:
                    yield self.finding(
                        ctx, fn.lineno,
                        f"mutable default argument in "
                        f"`{fn.name}` — use None and create inside")
        for expr in self._walk_import_time(ctx.tree.body):
            # lambda bodies never run at import — the lazy-accessor
            # pattern (`get_devices = lambda: jax.devices()`) is the fix,
            # not a violation (_walk_skipping always descends from its
            # root, so a default arg that IS a lambda must be skipped here)
            if isinstance(expr, ast.Lambda):
                continue
            for node in _walk_skipping(expr, skip_types=(ast.Lambda,)):
                if isinstance(node, ast.Call):
                    d = self._device_call(node, ctx)
                    if d:
                        yield self.finding(
                            ctx, node.lineno,
                            f"`{d}(...)` at import time pins the backend "
                            f"before multi-host init — build devices/"
                            f"meshes lazily (cf. parallel/mesh.py "
                            f"global_mesh())")


# ---------------------------------------------------------------------------
# ZL007 — swallowed exceptions in retry paths
# ---------------------------------------------------------------------------

def _in_serving_hot_path(path: str) -> bool:
    """Whether a file lives in the serving / inference retry paths (the
    rules that escalate there: ZL007's swallow-pass, ZL010's unbounded
    spins). Absolutized so severity tracks the file's real location, not
    how the scan path was spelled (a cwd-relative `server.py` must gate
    exactly like CI's absolute-path scan of the same file)."""
    if os.path.exists(path):
        path = os.path.abspath(path)
    p = path.replace("\\", "/")
    return ("/serving/" in p or p.startswith("serving/")
            or "/pipeline/inference/" in p
            or p.startswith("pipeline/inference/"))


@register
class SwallowedException(Rule):
    """A bare ``except:`` (which also catches ``KeyboardInterrupt`` /
    ``SystemExit``) or an ``except Exception: pass`` turns a dead model
    replica or a poisoned request into silence — the serving loop keeps
    accepting work it can never answer. Bare excepts are errors
    everywhere; swallow-``pass`` is an error in the ``serving/`` and
    ``pipeline/inference/`` retry paths and a warning elsewhere."""

    id = "ZL007"
    severity = ERROR

    def _in_hot_path(self, path: str) -> bool:
        return _in_serving_hot_path(path)

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        for st in handler.body:
            if isinstance(st, ast.Pass) or isinstance(st, ast.Continue):
                continue
            if isinstance(st, ast.Expr) and \
                    isinstance(st.value, ast.Constant):
                continue    # docstring / Ellipsis
            return False
        return True

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                # a re-raise only counts in the handler's own scope — a
                # `raise` inside a nested def/lambda does not run here
                nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                if any(isinstance(st, ast.Raise) for sub in node.body
                       if not isinstance(sub, nested)
                       for st in _walk_skipping(sub, skip_types=nested)):
                    continue    # bare except that re-raises: tolerated
                yield self.finding(
                    ctx, node.lineno,
                    "bare `except:` swallows KeyboardInterrupt/SystemExit "
                    "— catch Exception (and log) at most")
                continue
            if isinstance(node.type, ast.Tuple):
                # `except (Exception,):` / `except (Exception, ...)`
                names = [dotted(e) for e in node.type.elts]
                d = next((n for n in names
                          if n in ("Exception", "BaseException")), None)
            else:
                d = dotted(node.type)
            if d in ("Exception", "BaseException") and self._swallows(node):
                sev = ERROR if self._in_hot_path(ctx.path) else WARNING
                yield self.finding(
                    ctx, node.lineno,
                    f"`except {d}: pass` swallows errors"
                    + (" in a serving/inference retry path — log and "
                       "surface them" if sev == ERROR
                       else " — log them at least"),
                    severity=sev)


# ---------------------------------------------------------------------------
# ZL008 — missing donate_argnums on a rebinding step (warn)
# ---------------------------------------------------------------------------

@register
class MissingDonation(Rule):
    """A jitted step that re-binds its first argument (``params = ...``)
    produces a new buffer while the old one stays live — double the
    parameter HBM footprint per step. ``donate_argnums=(0,)`` lets XLA
    reuse the input buffer in place (cf. training.py's steps). Donation
    is wrong when the caller keeps using the input — such sites carry a
    justified suppression (cf. ``pipeline/inference/inference_model.py``);
    error severity since the package-wide triage (ROADMAP follow-up)."""

    id = "ZL008"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for info in ctx.jitted.values():
            if info.donates:
                continue
            fn = info.fn
            names = [n for n in param_names(fn) if n not in ("self", "cls")]
            if not names:
                continue
            first = names[0]
            rebinds = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                else:
                    continue
                if ctx.in_nested_scope(node, fn):
                    continue
                if any(isinstance(sub, ast.Name) and sub.id == first
                       for t in targets for sub in ast.walk(t)):
                    rebinds = True
                    break
            if rebinds:
                yield self.finding(
                    ctx, info.anchor_line,
                    f"jitted `{getattr(fn, 'name', '<fn>')}` re-binds its "
                    f"first argument `{first}` but declares no "
                    f"donate_argnums — the old buffer stays live (2x param "
                    f"HBM); add donate_argnums=(0,) if the caller discards "
                    f"its input")


# ---------------------------------------------------------------------------
# ZL009 — unbatched host→device transfer in a loop
# ---------------------------------------------------------------------------

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@register
class UnbatchedTransferInLoop(Rule):
    """``jax.device_put`` (or the implicit upload in ``jnp.asarray`` /
    ``jnp.array``) on a per-iteration value inside a Python ``for``/
    ``while`` body issues one small host→device transfer per element,
    each paying the full dispatch round-trip (milliseconds on a tunneled
    device) where one stacked transfer — or ``FeatureSet``'s
    ``prefetch_to_device`` pipeline — pays it once. Flags transfers whose
    argument derives from the loop variable (``for``) or from a name
    rebound each iteration (``while``); intentionally-chunked bulk
    uploads carry a justified suppression (cf.
    ``pipeline/inference/inference_model.py``)."""

    id = "ZL009"
    severity = ERROR

    def _transfer_call(self, ctx: ModuleContext,
                       node: ast.Call) -> Optional[str]:
        """The dotted name iff this call uploads its first argument to
        device — import-resolved (like ZL003's device_get) so a local
        helper named ``device_put`` or a non-jax ``asarray`` is never
        flagged."""
        d = dotted(node.func)
        if not d or not node.args:
            return None
        mods, froms = ctx.jax_names
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            if leaf == "device_put" and prefix.split(".", 1)[0] in mods:
                return d
            if leaf in ("asarray", "array") \
                    and prefix in ctx.aliases["jax.numpy"]:
                return d
        else:
            if froms.get(d) == "device_put":
                return d
            if ctx.from_imported("jax.numpy").get(d) in ("asarray", "array"):
                return d
        return None

    @staticmethod
    def _binding_names(target) -> Iterator[str]:
        """Names in BINDING position (``x``, ``x, y = ...``, ``*rest``) —
        ``obj.attr = v`` / ``d[k] = v`` assign THROUGH the name without
        rebinding it, so they do not make it per-iteration state."""
        stack = [target]
        while stack:
            t = stack.pop()
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)

    @staticmethod
    def _references(node: ast.AST, names: Set[str]) -> bool:
        return any(isinstance(sub, ast.Name) and sub.id in names
                   for sub in ast.walk(node))

    def _check_loop(self, ctx: ModuleContext, loop) -> Iterator[Finding]:
        body = [n for st in loop.body
                for n in _walk_skipping(st, skip_types=_NESTED_SCOPES)]
        seeds: Set[str] = set()
        if isinstance(loop, (ast.For, ast.AsyncFor)):
            seeds.update(self._binding_names(loop.target))
        else:
            # while: anything rebound in the body is per-iteration state —
            # and so is a walrus target in the CONDITION, the idiomatic
            # `while (item := q.get()) is not None:` streaming form
            for n in ast.walk(loop.test):
                if isinstance(n, ast.NamedExpr):
                    seeds.update(self._binding_names(n.target))
            for n in body:
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        seeds.update(self._binding_names(t))
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign,
                                    ast.NamedExpr)):
                    seeds.update(self._binding_names(n.target))
                elif isinstance(n, (ast.For, ast.AsyncFor)):
                    seeds.update(self._binding_names(n.target))
        # propagate derivation: `chunk = f(i)` makes `chunk` per-iteration,
        # and a comprehension over a seed binds per-iteration targets; two
        # passes close realistic chains without a full fixpoint
        for _ in range(2):
            for n in body:
                if isinstance(n, ast.Assign) \
                        and self._references(n.value, seeds):
                    for t in n.targets:
                        seeds.update(self._binding_names(t))
                elif isinstance(n, ast.NamedExpr) \
                        and self._references(n.value, seeds):
                    seeds.update(self._binding_names(n.target))
                elif isinstance(n, (ast.ListComp, ast.SetComp,
                                    ast.GeneratorExp, ast.DictComp)):
                    for gen in n.generators:
                        if self._references(gen.iter, seeds):
                            seeds.update(self._binding_names(gen.target))
        if not seeds:
            return
        for n in body:
            if not isinstance(n, ast.Call):
                continue
            d = self._transfer_call(ctx, n)
            if d is None or not self._references(n.args[0], seeds):
                continue
            # `device_put(jnp.asarray(x), ...)` is ONE transfer: flag the
            # outer call only
            par = ctx.parent(n)
            if isinstance(par, ast.Call) \
                    and self._transfer_call(ctx, par) is not None:
                continue
            yield self.finding(
                ctx, n.lineno,
                f"`{d}(...)` on a per-iteration value inside a loop — one "
                f"small host→device transfer (and dispatch round-trip) per "
                f"element; stack on the host and transfer once, or stream "
                f"through feature.prefetch_to_device")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # loops inside jit-traced code unroll at TRACE time — jnp.asarray
        # on a traced value is free there and device_put of a constant is
        # baked into the program, so no per-iteration runtime transfer
        # exists to flag
        traced = {id(info.fn) for info in ctx.jitted.values()}
        traced.update(id(fn) for fn in ctx.scan_bodies)
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            cur = loop
            while cur is not None and id(cur) not in traced:
                cur = ctx.parent(cur)
            if cur is not None:
                continue
            yield from self._check_loop(ctx, loop)


# ---------------------------------------------------------------------------
# ZL010 — unbounded time.sleep retry spin
# ---------------------------------------------------------------------------

_CLOCK_LEAVES = {"monotonic", "monotonic_ns", "time", "time_ns",
                 "perf_counter", "perf_counter_ns"}


@register
class UnboundedRetrySpin(Rule):
    """A ``while`` loop that ``time.sleep``-polls with no deadline — no
    clock read anywhere in the loop's test or body — waits forever when
    the condition never comes true: a dead backend turns the caller into
    a silently hung thread (the pre-reliability ``InputQueue.enqueue``
    full-stream spin). Route the wait through
    ``common.reliability.RetryPolicy`` (``delays()`` / ``wait_for`` with
    a deadline, a bounded ``for`` — never flagged) or check a
    ``time.monotonic()`` deadline in the loop. Error severity in the
    ``serving/`` and ``pipeline/inference/`` paths, warning elsewhere
    (an intentional forever-guard like ``ray/raycontext.py``'s
    parent-watch carries the warning knowingly)."""

    id = "ZL010"
    severity = ERROR

    def _is_sleep(self, ctx: ModuleContext, node: ast.Call) -> bool:
        d = dotted(node.func)
        if not d:
            return False
        if ctx.is_call_to(d, "time", ("sleep",)):
            return True
        return "." not in d and ctx.from_imported("time").get(d) == "sleep"

    def _is_clock_read(self, ctx: ModuleContext, node: ast.Call) -> bool:
        d = dotted(node.func)
        if not d:
            return False
        if ctx.is_call_to(d, "time", _CLOCK_LEAVES):
            return True
        return "." not in d and \
            ctx.from_imported("time").get(d) in _CLOCK_LEAVES

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, ast.While):
                continue
            scope = list(ast.walk(loop.test)) \
                + [n for st in loop.body if not isinstance(st, nested)
                   for n in _walk_skipping(st, skip_types=nested)]
            sleeps = [n for n in scope if isinstance(n, ast.Call)
                      and self._is_sleep(ctx, n)]
            if not sleeps:
                continue
            if any(isinstance(n, ast.Call) and self._is_clock_read(ctx, n)
                   for n in scope):
                continue        # a clock read implies a deadline check
            sev = ERROR if _in_serving_hot_path(ctx.path) else WARNING
            yield self.finding(
                ctx, sleeps[0].lineno,
                "time.sleep retry spin with no deadline in a `while` loop"
                + (" in a serving/inference path" if sev == ERROR else "")
                + " — bound it through common.reliability.RetryPolicy "
                  "(delays()/wait_for) or check a time.monotonic() "
                  "deadline",
                severity=sev)


# ---------------------------------------------------------------------------
# ZL011 — unbounded queue.Queue / blocking put with no timeout
# ---------------------------------------------------------------------------

_QUEUE_CLASSES = ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue")


@register
class UnboundedQueueUse(Rule):
    """An unbounded ``queue.Queue()`` between pipeline stages removes the
    backpressure the serving path depends on — a stalled consumer lets
    the producer buffer without limit until the host OOMs (the failure
    mode the bounded publisher queue exists to prevent). And a blocking
    ``.put()`` with no ``timeout`` on a BOUNDED queue is the same hang
    ZL010 flags for sleep spins: when the consumer wedges, the producer
    thread parks forever instead of surfacing the stall. Bound the queue
    (``maxsize=``) and the put (``timeout=`` + handle ``queue.Full``, or
    ``put_nowait``/``block=False`` where dropping is correct). Error
    severity in the ``serving/`` and ``pipeline/inference/`` paths,
    warning elsewhere (a deliberately unbounded hand-off carries the
    warning knowingly, with a justified suppression)."""

    id = "ZL011"
    severity = ERROR

    def _is_queue_ctor(self, ctx: ModuleContext, node: ast.Call) -> bool:
        d = dotted(node.func)
        if not d:
            return False
        if ctx.is_call_to(d, "queue", _QUEUE_CLASSES):
            return True
        return "." not in d and \
            ctx.from_imported("queue").get(d) in _QUEUE_CLASSES

    @staticmethod
    def _maxsize(node: ast.Call) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == "maxsize":
                return kw.value
        return node.args[0] if node.args else None

    @staticmethod
    def _target_leaf(t: ast.AST) -> Optional[str]:
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return t.attr
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        sev = ERROR if _in_serving_hot_path(ctx.path) else WARNING
        # names bound to a queue constructor anywhere in the module
        # (`q = queue.Queue(...)`, `self._pub_queue = queue.Queue(...)`,
        # annotated forms included): the receivers whose `.put` calls
        # this rule attributes to a stdlib queue rather than to some
        # unrelated object's put method
        qnames = set()
        for node in ast.walk(ctx.tree):
            value = getattr(node, "value", None)
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and isinstance(value, ast.Call) \
                    and self._is_queue_ctor(ctx, value):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    leaf = self._target_leaf(t)
                    if leaf:
                        qnames.add(leaf)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._is_queue_ctor(ctx, node):
                d = dotted(node.func) or "queue.Queue"
                if d.rsplit(".", 1)[-1] == "SimpleQueue":
                    # SimpleQueue cannot be bounded at all
                    yield self.finding(
                        ctx, node.lineno,
                        "queue.SimpleQueue() is always unbounded — a "
                        "stalled consumer buffers without limit; use "
                        "queue.Queue(maxsize=...) so the producer "
                        "backpressures",
                        severity=sev)
                    continue
                size = self._maxsize(node)
                if size is None or (isinstance(size, ast.Constant)
                                    and isinstance(size.value, (int, float))
                                    and not isinstance(size.value, bool)
                                    and size.value <= 0):
                    yield self.finding(
                        ctx, node.lineno,
                        f"{d}() with no positive maxsize is unbounded"
                        + (" in a serving/inference path"
                           if sev == ERROR else "")
                        + " — a stalled consumer buffers without limit; "
                          "pass maxsize= so the producer backpressures",
                        severity=sev)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "put" \
                    and self._target_leaf(node.func.value) in qnames:
                # Queue.put(item, block=True, timeout=None): both block
                # and timeout may be passed positionally
                if any(kw.arg == "timeout" for kw in node.keywords) \
                        or len(node.args) >= 3:
                    continue
                block_arg = None
                for kw in node.keywords:
                    if kw.arg == "block":
                        block_arg = kw.value
                if block_arg is None and len(node.args) >= 2:
                    block_arg = node.args[1]
                if isinstance(block_arg, ast.Constant) \
                        and block_arg.value is False:
                    continue    # non-blocking put raises Full immediately
                yield self.finding(
                    ctx, node.lineno,
                    "blocking .put() on a queue with no timeout"
                    + (" in a serving/inference path" if sev == ERROR
                       else "")
                    + " — a wedged consumer parks this thread forever; "
                      "pass timeout= and handle queue.Full (or "
                      "put_nowait/block=False where dropping is correct)",
                    severity=sev)


# ---------------------------------------------------------------------------
# ZL012 — full-vocab cross-entropy materialization in a training path
# ---------------------------------------------------------------------------

def _in_training_hot_path(path: str) -> bool:
    """Whether a file lives in the keras training engine — the paths where
    a full-logits cross-entropy lands on the LM-head training hot loop
    (objectives, the step builders, the estimator driver). Absolutized
    like ``_in_serving_hot_path`` so severity tracks the file's real
    location."""
    if os.path.exists(path):
        path = os.path.abspath(path)
    p = path.replace("\\", "/")
    return ("/pipeline/api/keras/" in p or p.startswith("pipeline/api/keras/")
            or "/pipeline/estimator/" in p
            or p.startswith("pipeline/estimator/"))


@register
class FullVocabCrossEntropy(Rule):
    """``log_softmax`` over full logits followed by a label pick
    (``take_along_axis`` / ``one_hot``) is the sparse-cross-entropy shape
    that materializes the ``(N, V)`` log-probability tensor — three times
    over, counting the softmax backward and the pick's scatter. At LM-head
    vocab widths that is gigabytes of fp32 HBM traffic per step (the 32k
    long-context bench budgeted 2 GB for it at 4k seq). Training-path
    sparse CE should stream through ``ops.fused_cross_entropy`` (chunked
    online logsumexp + label logit, O(chunk·V) memory, custom VJP) — the
    keras loss resolution does this automatically for big-vocab Dense
    heads (``zoo.train.fused_ce``). Error severity in the keras training
    engine (``pipeline/api/keras/``, ``pipeline/estimator/``); warning
    elsewhere — a small-class head where full logits are harmless, or the
    equivalence oracle itself, carries a justified suppression."""

    id = "ZL012"
    severity = ERROR

    def _is_log_softmax(self, ctx: ModuleContext, node: ast.Call) -> bool:
        d = dotted(node.func)
        if not d:
            return False
        mods, froms = ctx.jax_names
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            return leaf == "log_softmax" and prefix.split(".", 1)[0] in mods
        return froms.get(d) == "log_softmax"

    def _is_label_pick(self, ctx: ModuleContext, node: ast.Call) -> bool:
        d = dotted(node.func)
        if not d:
            return False
        mods, froms = ctx.jax_names
        if "." in d:
            prefix, leaf = d.rsplit(".", 1)
            if leaf == "take_along_axis" \
                    and prefix in ctx.aliases["jax.numpy"]:
                return True
            return leaf == "one_hot" and prefix.split(".", 1)[0] in mods
        if ctx.from_imported("jax.numpy").get(d) == "take_along_axis":
            return True
        return froms.get(d) == "one_hot"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        sev = ERROR if _in_training_hot_path(ctx.path) else WARNING
        nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        scopes = [ctx.tree] + list(ctx.functions()) + [
            n for n in ast.walk(ctx.tree) if isinstance(n, ast.Lambda)]
        for scope in scopes:
            body = scope.body if isinstance(scope.body, list) \
                else [scope.body]
            # nested functions/lambdas are their own scope — a module-level
            # walk must not merge two different functions' calls into one
            # fake cross-entropy
            calls = [n for st in body if not isinstance(st, nested)
                     for n in _walk_skipping(st, skip_types=nested)
                     if isinstance(n, ast.Call)]
            softmaxes = [n for n in calls if self._is_log_softmax(ctx, n)]
            if not softmaxes:
                continue
            if not any(self._is_label_pick(ctx, n) for n in calls):
                continue
            yield self.finding(
                ctx, softmaxes[0].lineno,
                "full-vocab log_softmax + label pick materializes the "
                "(N, V) log-probability tensor"
                + (" in a training path" if sev == ERROR else "")
                + " — stream it through ops.fused_cross_entropy "
                  "(fused_sparse_cross_entropy: chunked logsumexp + label "
                  "logit, O(chunk*V) memory; the keras loss resolution "
                  "picks it up via zoo.train.fused_ce)",
                severity=sev)


# ---------------------------------------------------------------------------
# ZL013 — bare Python assert on traced values inside jit-staged bodies
# ---------------------------------------------------------------------------

def _in_package(path: str) -> bool:
    """Whether a file is package code (``analytics_zoo_tpu/``) — where a
    compiled-away assertion is a shipped latent bug, so ZL013 runs at
    error severity; elsewhere (tests, examples, user scripts) it warns."""
    if os.path.exists(path):
        path = os.path.abspath(path)
    p = path.replace("\\", "/")
    return "/analytics_zoo_tpu/" in p or p.startswith("analytics_zoo_tpu/")


@register
class TracedAssert(Rule):
    """A bare Python ``assert`` on a traced value inside a jit-staged
    body is a guard that cannot guard: at trace time the tracer either
    raises ``TracerBoolConversionError`` (boolean contexts) or — the
    insidious form — the assert evaluates ONCE on the abstract value,
    is baked out of the compiled program, and never runs again on real
    data (and under ``python -O`` asserts vanish entirely). A numeric
    invariant the author meant to enforce per step silently enforces
    nothing. Use ``checkify.check`` / ``jax.debug`` for a real runtime
    check, branch on static metadata (``x.shape`` asserts are fine and
    not flagged), or return a packed sentinel flag the host inspects
    (the ``common/anomaly.py`` pattern). Error severity in package
    code; warning elsewhere."""

    id = "ZL013"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        sev = ERROR if _in_package(ctx.path) else WARNING
        bodies: List[Tuple[ast.AST, Set[str], str]] = []
        for info in ctx.jitted.values():
            fn = info.fn
            if not hasattr(fn, "args"):
                continue
            bodies.append((fn, _traced_params(info),
                           getattr(fn, "name", "<fn>")))
        for fn in ctx.scan_bodies:
            if hasattr(fn, "args"):   # every param of a scan body traces
                traced = set(param_names(fn)) - {"self", "cls"}
                bodies.append((fn, traced,
                               getattr(fn, "name", "<lambda>")))
        seen: Set[int] = set()
        for fn, traced, name in bodies:
            if id(fn) in seen or not traced:
                continue
            seen.add(id(fn))
            # derivation-aware (the ZL009 discipline): a local assigned
            # from a traced value is itself traced (`y = jnp.dot(x, w);
            # assert y.sum() > 0`). Taint propagates through the static-
            # metadata filter, so `n = x.shape[0]` stays untainted.
            derived = set(traced)
            # one AST walk collects the candidate assignments; the
            # fixpoint then iterates only over that list (a long
            # derivation chain must not re-walk the whole body per
            # newly-tainted name)
            assigns = [node for node in ast.walk(fn)
                       if isinstance(node, (ast.Assign, ast.AugAssign))
                       and not ctx.in_nested_scope(node, fn)]
            changed = True
            while changed:
                changed = False
                for node in assigns:
                    if not _traced_name_in_expr(ctx, node.value, derived):
                        continue
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        for tn in ast.walk(t):
                            if isinstance(tn, ast.Name) \
                                    and tn.id not in derived:
                                derived.add(tn.id)
                                changed = True
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assert):
                    continue
                if ctx.in_nested_scope(node, fn):   # own scope: shadows
                    continue
                offender = _traced_name_in_expr(ctx, node.test, derived)
                if offender:
                    yield self.finding(
                        ctx, node.lineno,
                        f"bare `assert` on traced value `{offender}` in "
                        f"jit-staged `{name}` — evaluated once at trace "
                        f"time (or TracerBoolConversionError), never on "
                        f"real data; use checkify.check/jax.debug, "
                        f"assert static metadata, or return a sentinel "
                        f"flag the host checks", severity=sev)


# ---------------------------------------------------------------------------
# ZL014 — thread-shared state without lock discipline
# ---------------------------------------------------------------------------

def _threading_ctor_names(ctx: ModuleContext,
                          leaves: Tuple[str, ...]) -> Tuple[Set[str],
                                                            Set[str]]:
    """``(prefixes, bare)`` local spellings of ``threading.<leaf>`` for
    the given leaves — module aliases (``import threading as th``) and
    from-imports (``from threading import Thread as T``)."""
    prefixes = set(ctx.aliases.get("threading", {"threading"}))
    bare = {local for local, orig in ctx.from_imported("threading").items()
            if orig in leaves}
    return prefixes, bare


def _is_threading_call(ctx: ModuleContext, node: ast.AST,
                       leaves: Tuple[str, ...]) -> bool:
    d = dotted(node)
    if d is None:
        return False
    prefixes, bare = _threading_ctor_names(ctx, leaves)
    if "." in d:
        prefix, leaf = d.rsplit(".", 1)
        return leaf in leaves and prefix in prefixes
    return d in bare


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@register
class ThreadSharedWriteDiscipline(Rule):
    """**Thread-shared instance state without lock discipline.** A class
    that runs several of its methods on different threads (the serving
    server: serve loop + publisher + heartbeat/reclaim) and writes the
    same instance attribute from more than one of those thread entry
    points is relying on the GIL making each *individual* bytecode
    atomic — read-modify-write sequences interleave, and the bug
    surfaces only under production concurrency. Interprocedural within
    the class: thread roots are the methods handed to
    ``threading.Thread(target=..., args=(...))``, writes are attributed
    through the intra-class call graph, and a write counts as guarded
    only when every path to it holds the same ``threading.Lock``
    attribute (``with self._lock:`` at the write or around every call
    site leading to it). Error in the ``serving/`` and
    ``pipeline/inference/`` paths, warning elsewhere."""

    id = "ZL014"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        sev = ERROR if _in_serving_hot_path(ctx.path) else WARNING
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node, sev)

    # -- per-class facts ----------------------------------------------------
    def _methods(self, cls: ast.ClassDef) -> Dict[str, ast.AST]:
        return {n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _lock_attrs(self, ctx: ModuleContext, cls: ast.ClassDef,
                    methods: Dict[str, ast.AST]) -> Set[str]:
        """Attributes assigned ``threading.Lock()``/``RLock()``/
        ``Condition()`` anywhere in the class."""
        out: Set[str] = set()
        for fn in methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        _is_threading_call(ctx, node.value.func,
                                           ("Lock", "RLock", "Condition")):
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            out.add(attr)
        return out

    def _thread_contexts(self, ctx: ModuleContext, cls: ast.ClassDef,
                         methods: Dict[str, ast.AST]) -> List[Set[str]]:
        """One entry per thread the class can spawn: the set of
        own-method names a ``threading.Thread(...)`` creation may run
        (the target plus any method reference passed through ``args=``
        / ``kwargs=`` — the ``Thread(target=self._supervised,
        args=("serve", self._loop))`` trampoline idiom). A creation
        site lexically inside a loop (or comprehension) spawns the same
        roots CONCURRENTLY with themselves — the worker-pool pattern —
        so it contributes two contexts."""
        out: List[Set[str]] = []
        for fn in methods.values():
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and _is_threading_call(ctx, node.func, ("Thread",))):
                    continue
                roots: Set[str] = set()
                for sub in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    for ref in ast.walk(sub):
                        attr = _self_attr(ref)
                        if attr and attr in methods:
                            roots.add(attr)
                if not roots:
                    continue
                out.append(roots)
                cur = ctx.parent(node)
                while cur is not None and cur is not fn:
                    if isinstance(cur, (ast.For, ast.AsyncFor, ast.While,
                                        ast.ListComp, ast.SetComp,
                                        ast.GeneratorExp)):
                        out.append(set(roots))   # N spawns race each other
                        break
                    cur = ctx.parent(cur)
        return out

    def _call_edges(self, methods: Dict[str, ast.AST],
                    lock_attrs: Set[str]):
        """``(caller, callee, locks_held_at_site)`` for every own-method
        reference inside a method body — direct ``self.m()`` calls and
        method references passed around as callbacks (conservative:
        a referenced method may run)."""
        edges = []
        for name, fn in methods.items():
            for node in ast.walk(fn):
                attr = _self_attr(node)
                if attr and attr in methods and attr != name and \
                        isinstance(node.ctx, ast.Load):
                    edges.append((name, attr,
                                  self._locks_at(node, fn, lock_attrs)))
        return edges

    @staticmethod
    def _locks_at(node: ast.AST, fn: ast.AST,
                  lock_attrs: Set[str]) -> Set[str]:
        """Lock attributes held at ``node`` — enclosing ``with
        self.<lock>:`` blocks up to the method root."""
        held: Set[str] = set()
        cur = getattr(node, "_zl_parent", None)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    attr = _self_attr(item.context_expr)
                    if attr and attr in lock_attrs:
                        held.add(attr)
            cur = getattr(cur, "_zl_parent", None)
        return held

    def _check_class(self, ctx: ModuleContext, cls: ast.ClassDef,
                     sev: str) -> Iterator[Finding]:
        methods = self._methods(cls)
        contexts = self._thread_contexts(ctx, cls, methods)
        if len(contexts) < 2:
            return      # fewer than two thread entry points: no sharing
        lock_attrs = self._lock_attrs(ctx, cls, methods)
        edges = self._call_edges(methods, lock_attrs)

        # reachability per thread context over the call graph
        reach: List[Set[str]] = []
        for roots in contexts:
            seen = set(roots)
            frontier = list(roots)
            while frontier:
                cur = frontier.pop()
                for caller, callee, _ in edges:
                    if caller == cur and callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)
            reach.append(seen)
        threaded: Set[str] = set().union(*reach)

        # minimal locks guaranteed held on ENTRY to each method: the
        # intersection over every known call site's (locks at site +
        # caller's own guaranteed locks). Thread roots hold none; other
        # methods start UNKNOWN and only take a value once a known
        # caller reaches them — starting them at "no locks" instead
        # would poison the meet (X & anything = X) and un-guard every
        # callee of an always-locked helper
        roots_all: Set[str] = set().union(*contexts)
        inherited: Dict[str, Set[str]] = {m: set() for m in roots_all}
        for _ in range(len(methods) + 1):
            changed = False
            for m in threaded - roots_all:
                sites = [locks | inherited[caller]
                         for caller, callee, locks in edges
                         if callee == m and caller in inherited]
                if not sites:
                    continue            # no known caller yet
                new = set.intersection(*sites)
                if inherited.get(m) != new:
                    inherited[m] = new
                    changed = True
            if not changed:
                break

        # writes: self.<attr> = / += / self.<attr>[k] = inside threaded
        # methods, with the locks held at the write site
        writes: Dict[str, List[Tuple[str, int, Set[str]]]] = {}
        for name in threaded:
            fn = methods.get(name)
            if fn is None:
                continue
            for node in ast.walk(fn):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None and isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                    if attr is None or attr in lock_attrs:
                        continue
                    held = self._locks_at(node, fn, lock_attrs) \
                        | inherited.get(name, set())
                    writes.setdefault(attr, []).append(
                        (name, node.lineno, held))

        for attr in sorted(writes):
            ws = writes[attr]
            hit = [i for i, r in enumerate(reach)
                   if any(w[0] in r for w in ws)]
            if len(hit) < 2:
                continue
            common = set.intersection(*(w[2] for w in ws))
            if common:
                continue
            first = min(ws, key=lambda w: w[1])
            methods_writing = sorted({w[0] for w in ws})
            yield self.finding(
                ctx, first[1],
                f"attribute `self.{attr}` is written from "
                f"{len(hit)} thread entry points "
                f"({', '.join(methods_writing)}) without one shared "
                f"threading.Lock guarding every write path — "
                f"read-modify-write interleavings corrupt it under "
                f"load; wrap the writes in `with self.<lock>:`",
                severity=sev)


# ---------------------------------------------------------------------------
# ZL015 — metric naming / labeling convention drift
# ---------------------------------------------------------------------------

#: non-base-unit duration suffixes (OBSERVABILITY.md: durations are
#: `_seconds`, quantile summaries `_quantiles_seconds`)
_BAD_UNIT_SUFFIXES = ("_ms", "_msec", "_millis", "_milliseconds", "_us",
                      "_micros", "_microseconds", "_ns", "_nanos",
                      "_nanoseconds", "_mins", "_minutes", "_hours",
                      "_days", "_sec", "_secs")


@register
class MetricNamingDrift(Rule):
    """**Metric naming/labeling drift.** The OBSERVABILITY.md convention
    (``zoo_<layer>_<what>[_unit]``; counters end ``_total``, durations
    ``_seconds``, summaries ``_quantiles_seconds``) is what dashboards
    and the catalog reconciliation key on — a misnamed family is
    invisible to both. Worse is cardinality: a label whose value comes
    from request data (a uri, a trace id) mints one series per distinct
    value and grows the registry without bound — label values must be
    constants, literal-loop enumerations, or a justified bounded set
    (suppress with the rationale). Error in package code, warning
    elsewhere."""

    id = "ZL015"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        from .contracts import iter_metric_sites
        sev = ERROR if _in_package(ctx.path) else WARNING
        for s in iter_metric_sites(ctx):
            if s.name is None:
                yield self.finding(
                    ctx, s.line,
                    "metric name is not statically resolvable — use a "
                    "string constant (or constant f-string) so the "
                    "catalog reconciliation can see the family",
                    severity=sev)
            else:
                yield from self._name_findings(ctx, s, sev)
            if s.opaque_labels:
                yield self.finding(
                    ctx, s.line,
                    "labels= is not a dict literal — the label keys "
                    "cannot be checked against the catalog; inline the "
                    "dict", severity=sev)
            for key in s.dynamic_label_keys:
                yield self.finding(
                    ctx, s.line,
                    f"label '{key}' takes a runtime value here — "
                    f"unbounded series cardinality if it derives from "
                    f"request data; use constants or a literal "
                    f"enumeration (or suppress with the bounded-set "
                    f"rationale)", severity=sev)

    def _name_findings(self, ctx: ModuleContext, s,
                       sev: str) -> Iterator[Finding]:
        name = s.name
        plain = name.replace("*", "")
        if not re.match(r"[a-z*][a-z0-9_*]*\Z", name):
            yield self.finding(
                ctx, s.line,
                f"metric name '{name}' is not a valid Prometheus "
                f"family name ([a-z][a-z0-9_]*)", severity=sev)
            return
        if not name.startswith("zoo_") and not name.startswith("*"):
            yield self.finding(
                ctx, s.line,
                f"metric name '{name}' is not `zoo_`-prefixed — the "
                f"package namespace every dashboard and the catalog "
                f"key on", severity=sev)
        wildcard_tail = name.endswith("*")
        if s.kind == "counter" and not wildcard_tail \
                and not name.endswith("_total"):
            yield self.finding(
                ctx, s.line,
                f"counter '{name}' must end in `_total` (Prometheus "
                f"rate() semantics key on the suffix)", severity=sev)
        if s.kind in ("gauge", "histogram") and name.endswith("_total"):
            yield self.finding(
                ctx, s.line,
                f"{s.kind} '{name}' ends in `_total` — that suffix "
                f"promises a monotonic counter", severity=sev)
        if s.kind == "summary" and not wildcard_tail \
                and not name.endswith("_quantiles_seconds"):
            yield self.finding(
                ctx, s.line,
                f"summary '{name}' must end in `_quantiles_seconds` "
                f"(the histogram sibling keeps the bare `_seconds` "
                f"name)", severity=sev)
        for suf in _BAD_UNIT_SUFFIXES:
            # `_per_<unit>` names are RATES (records_per_sec), not
            # durations — the unit there is a denominator, not a quantity
            if plain.endswith(suf) and "_per" + suf not in plain:
                yield self.finding(
                    ctx, s.line,
                    f"metric name '{name}' uses a non-base unit "
                    f"(`{suf}`) — durations are `_seconds` in the "
                    f"catalog convention", severity=sev)
                break
