"""zoolint — JAX/TPU-aware static analysis for the analytics_zoo_tpu stack.

An AST linter (no code execution, no jax import) with a pluggable rule
registry, targeting the staged-computation hazards runtime tests miss:
PRNG key reuse, host side effects and hidden syncs under ``jit``, Python
branches on traced values, import-time device/mesh construction, swallowed
exceptions in serving retry paths, missing buffer donation, and
unbatched host→device transfers in loops.

CLI:     ``python -m analytics_zoo_tpu.analysis [paths...]``
Gate:    ``tests/test_zoolint.py`` (tier-1) asserts zero errors.
Docs:    ``docs/guides/STATIC_ANALYSIS.md``
Silence: ``# zoolint: disable=ZL001`` on the flagged line.
"""

from .core import (ERROR, WARNING, Finding, ModuleContext, Rule, all_rules,
                   lint_file, lint_paths, lint_source, register)
from .cli import main

__all__ = ["ERROR", "WARNING", "Finding", "ModuleContext", "Rule",
           "all_rules", "lint_file", "lint_paths", "lint_source",
           "register", "main"]
