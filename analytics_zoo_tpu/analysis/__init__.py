"""zoolint — JAX/TPU-aware static analysis for the analytics_zoo_tpu stack.

An AST linter (no code execution, no jax import) with a pluggable rule
registry, targeting the staged-computation hazards runtime tests miss:
PRNG key reuse, host side effects and hidden syncs under ``jit``, Python
branches on traced values, import-time device/mesh construction, swallowed
exceptions in serving retry paths, missing buffer donation, unbatched
host→device transfers in loops, thread-shared state without lock
discipline, and metric naming/cardinality drift — plus a whole-project
**contract pass** (``--contracts``) that reconciles the runtime contract
surfaces (metric registrations, conf keys, fault sites incl. their test
coverage, rule ids) against their documented catalogs in both
directions, and a **device-semantics pass** (``device.py``, ZL021–ZL024)
that abstract-interprets staged and Pallas code for dtype-promotion
hazards, mesh-axis discipline, tile alignment and static VMEM budgets.

CLI:     ``python -m analytics_zoo_tpu.analysis [paths...] [--contracts]
         [--changed-only [--base REF]] [--ci] [--format json]``
Gate:    ``tests/test_zoolint.py`` (tier-1) asserts zero errors and a
         clean contract reconciliation.
Docs:    ``docs/guides/STATIC_ANALYSIS.md``
Silence: ``# zoolint: disable=ZL001`` on the flagged line (or the first
         line of the enclosing multi-line statement).
"""

from .core import (ERROR, WARNING, Finding, ModuleContext, Rule, all_rules,
                   lint_file, lint_paths, lint_source, register)
from .project import (ProjectContext, ProjectRule, all_project_rules,
                      lint_project, register_project)
from .cli import main

__all__ = ["ERROR", "WARNING", "Finding", "ModuleContext", "Rule",
           "all_rules", "lint_file", "lint_paths", "lint_source",
           "register", "ProjectContext", "ProjectRule",
           "all_project_rules", "lint_project", "register_project",
           "main"]
