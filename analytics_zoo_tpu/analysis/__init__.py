"""zoolint — JAX/TPU-aware static analysis for the analytics_zoo_tpu stack.

An AST linter (no code execution, no jax import) with a pluggable rule
registry, targeting the staged-computation hazards runtime tests miss:
PRNG key reuse, host side effects and hidden syncs under ``jit``, Python
branches on traced values, import-time device/mesh construction, swallowed
exceptions in serving retry paths, missing buffer donation, unbatched
host→device transfers in loops, thread-shared state without lock
discipline, and metric naming/cardinality drift — plus a whole-project
**contract pass** (``--contracts``) that reconciles the runtime contract
surfaces (metric registrations, conf keys, fault sites incl. their test
coverage, rule ids) against their documented catalogs in both
directions, and a **device-semantics pass** (``device.py``, ZL021–ZL024)
that abstract-interprets staged and Pallas code for dtype-promotion
hazards, mesh-axis discipline, tile alignment and static VMEM budgets,
and an **SPMD collective-semantics pass** (``spmd.py``, ZL025–ZL028)
that abstract-interprets ``shard_map`` bodies over a distribution-state
lattice (replicated / sharded / partial_sum / unknown) to catch unbound
collective axes, unreduced outputs escaping through ``out_specs``,
divergent collectives under traced control flow, and PartitionSpec
hygiene slips — with a collective catalog in PARALLELISM.md reconciled
both directions by ``--contracts``.

CLI:     ``python -m analytics_zoo_tpu.analysis [paths...] [--contracts]
         [--changed-only [--base REF]] [--ci [--profile]]
         [--format json|sarif]``
Gate:    ``tests/test_zoolint.py`` (tier-1) asserts zero errors and a
         clean contract reconciliation.
Docs:    ``docs/guides/STATIC_ANALYSIS.md``
Silence: ``# zoolint: disable=ZL001`` on the flagged line (or the first
         line of the enclosing multi-line statement).
"""

from .core import (ERROR, WARNING, Finding, ModuleContext, Rule, all_rules,
                   lint_file, lint_paths, lint_source, register)
from .project import (ProjectContext, ProjectRule, all_project_rules,
                      lint_project, register_project)
from .spmd import (DistState, dot_transfer, interp_source_fn,
                   iter_shard_map_sites, join)
from .cli import main

__all__ = ["ERROR", "WARNING", "Finding", "ModuleContext", "Rule",
           "all_rules", "lint_file", "lint_paths", "lint_source",
           "register", "ProjectContext", "ProjectRule",
           "all_project_rules", "lint_project", "register_project",
           "DistState", "dot_transfer", "interp_source_fn",
           "iter_shard_map_sites", "join", "main"]
