"""``python -m analytics_zoo_tpu.analysis`` — the zoolint command line.

Exit status: **0** clean, **1** when any ERROR-severity per-file
finding survives suppression (warnings never gate), **2** ONLY when the
``--contracts`` project pass (whole-package symbol index + the
code↔docs contract reconciliation, rules ZL016–ZL020 and ZL022's
declaration direction) itself finds drift, **3** on a usage error
(typo'd path/flag/rule id — never mistakable for drift). With no paths
it scans the installed ``analytics_zoo_tpu`` package plus the sibling
``tests/`` directory and ``bench.py`` when they exist — exactly what
the CI gate (`tests/test_zoolint.py`) runs; under ``--contracts`` each
package file is parsed once and shared between the per-file and
project passes.

``--changed-only`` scopes the per-file scan to files changed against
the merge-base with ``--base`` (default ``main``) plus untracked files
— fast local iteration; outside a git repo it degrades to the full
scan (the diff is read with ``--name-status`` so rename targets scan
too — ``--name-only`` prints a rename's OLD path, which no longer
exists). ``--ci`` is the one-invocation CI entry point: per-file +
``--contracts`` with findings mirrored as JSON lines to a results
file (schema ``RESULTS_SCHEMA``: a header object naming the rules
that ran, then one finding per line), configured by a committed
``.zoolint.json`` — the tier-1 gate and external CI run the identical
command (``scripts/zoolint --ci``).

``--format json`` emits one finding per line as a JSON object
(``rule``/``file``/``line``/``severity``/``message``) for CI and editor
consumption; ``--format sarif`` emits a single SARIF 2.1.0 document
(registry-sourced rule metadata, line-independent fingerprints) for
code-scanning UIs; in both, the human summary line moves to stderr.
``--profile`` prints per-rule wall-time to stderr after any scan.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from .core import (ERROR, all_rules, iter_py_files, lint_context,
                   lint_file, lint_paths)
from .project import ProjectContext, all_project_rules, lint_project


#: version of the ``--ci`` results-file format: line 1 is a header
#: object ``{"zoolint_results_schema": N, "rules": [ids that ran]}``,
#: every following line one finding object. Bump when the line shape
#: changes; ``.zoolint.json`` pins the schema CI expects.
RESULTS_SCHEMA = 2


class _Parser(argparse.ArgumentParser):
    """Usage errors exit 3, not argparse's default 2 — under
    ``--contracts`` exit 2 means "the contract surface drifted", and a
    typo'd flag must not read as phantom catalog drift to CI."""

    def error(self, message):
        self.print_usage(sys.stderr)
        self.exit(3, f"{self.prog}: error: {message}\n")


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_paths() -> List[str]:
    pkg = package_root()
    root = os.path.dirname(pkg)
    paths = [pkg]
    # keep in sync with tests/test_zoolint.py's gate scan — the bare CLI
    # must agree with what CI enforces
    for extra in (os.path.join(root, "tests"),
                  os.path.join(root, "bench.py")):
        if os.path.exists(extra):
            paths.append(extra)
    return paths


def _split_ids(value: Optional[str]) -> Optional[List[str]]:
    if not value:
        return None
    return [v.strip() for v in value.split(",") if v.strip()]


def _git(anchor: Optional[str], *cmd: str):
    return subprocess.run(
        ["git"] + (["-C", anchor] if anchor else []) + list(cmd),
        capture_output=True, text=True)


def git_changed_files(base: str,
                      anchor: Optional[str] = None) -> Optional[Set[str]]:
    """Realpaths of files changed in the working tree against the
    merge-base with ``base`` (untracked files included). ``anchor`` is a
    directory inside the repo the SCANNED tree belongs to — resolving
    from the process cwd instead would, from an unrelated repo, produce
    a changed set containing none of the scanned files and read as a
    silent green. None when git is unavailable or no work tree is found
    — the caller degrades to the full scan."""
    try:
        top = _git(anchor, "rev-parse", "--show-toplevel")
    except OSError:
        return None
    if top.returncode != 0:
        return None
    root = top.stdout.strip()
    names: Set[str] = set()
    mb = _git(anchor, "merge-base", base, "HEAD")
    ref = mb.stdout.strip() if mb.returncode == 0 else None
    if ref is None:
        # unknown base ref (fresh clone, renamed default branch):
        # diff against HEAD so local edits still scope, and say so
        print(f"zoolint: --base {base} has no merge-base here; "
              f"diffing against HEAD", file=sys.stderr)
        ref = "HEAD"
    # --name-status, not --name-only: under rename detection (-M, on by
    # default in many configs) --name-only prints the OLD path of a
    # rename — which no longer exists and silently drops the renamed
    # file from the scan. Status lines are TAB-separated; rename/copy
    # rows (R###/C###) carry "old<TAB>new" — keep both (the old path
    # vanishes harmlessly in iter_py_files; the NEW path is the fix).
    diff = _git(anchor, "diff", "--name-status", ref)
    if diff.returncode == 0:
        for ln in diff.stdout.splitlines():
            fields = ln.split("\t")
            if len(fields) < 2 or not fields[0].strip():
                continue
            status = fields[0].strip()
            if status[0] in ("R", "C") and len(fields) >= 3:
                names.update(f for f in fields[1:3] if f.strip())
            else:
                names.add(fields[1])
    untracked = _git(anchor, "ls-files", "--others", "--exclude-standard")
    if untracked.returncode == 0:
        names.update(ln for ln in untracked.stdout.splitlines()
                     if ln.strip())
    return {os.path.realpath(os.path.join(root, n)) for n in names}


def _sarif_doc(findings, contracts: bool) -> dict:
    """A SARIF 2.1.0 document: rule metadata straight from the
    registries (id, docstring, default level) and one result per
    finding. ``partialFingerprints`` hashes rule|file-basename|message —
    deliberately line-independent, so a finding that merely moves when
    unrelated lines are inserted keeps its identity in code-scanning
    UIs instead of reopening as new."""
    import hashlib
    import re
    rules_meta, seen = [], set()
    pool = list(all_rules()) + (list(all_project_rules())
                                if contracts else [])
    for rule in pool:
        if rule.id in seen:
            continue
        seen.add(rule.id)
        doc = " ".join((rule.__doc__ or "").split())
        rules_meta.append({
            "id": rule.id,
            "shortDescription": {"text": (doc or rule.id)[:280]},
            "defaultConfiguration": {
                "level": "error" if rule.severity == ERROR
                else "warning"},
        })
    results = []
    for f in findings:
        # digit runs are masked: messages routinely cite line numbers
        # ("key consumed on line 3"), which would defeat the
        # line-independence the fingerprint exists for
        norm = re.sub(r"\d+", "#", f.message)
        fp = hashlib.sha256(
            f"{f.rule_id}|{os.path.basename(f.path)}|{norm}"
            .encode("utf-8")).hexdigest()
        results.append({
            "ruleId": f.rule_id,
            "level": "error" if f.severity == ERROR else "warning",
            "message": {"text": f.message},
            "locations": [{"physicalLocation": {
                "artifactLocation": {
                    "uri": f.path.replace(os.sep, "/")},
                "region": {"startLine": max(int(f.line), 1)}}}],
            "partialFingerprints": {"zoolintFingerprint/v1": fp},
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "zoolint",
                                "rules": rules_meta}},
            "results": results,
        }],
    }


def _find_ci_config(paths: List[str]) -> Optional[str]:
    """``.zoolint.json`` next to the scanned tree: the cwd, then the
    directory holding the first scanned path, then the package root's
    parent (the repo root in the default layout)."""
    candidates = [os.getcwd()]
    if paths:
        candidates.append(os.path.dirname(os.path.abspath(paths[0]))
                          if os.path.isfile(paths[0])
                          else os.path.abspath(paths[0]))
        candidates.append(os.path.dirname(os.path.abspath(paths[0])))
    candidates.append(os.path.dirname(package_root()))
    for d in candidates:
        p = os.path.join(d, ".zoolint.json")
        if os.path.isfile(p):
            return p
    return None


def main(argv: Optional[List[str]] = None) -> int:
    ap = _Parser(
        prog="zoolint",
        description="JAX/TPU-aware static analysis for analytics_zoo_tpu "
                    "(PRNG reuse, host effects under jit, hidden syncs, "
                    "import-time device init, ...) plus the --contracts "
                    "project pass (code↔docs catalog reconciliation)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to scan (default: the "
                         "analytics_zoo_tpu package, tests/ and bench.py)")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", metavar="IDS",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--errors-only", action="store_true",
                    help="print (and count) only error-severity findings")
    ap.add_argument("--contracts", action="store_true",
                    help="also run the whole-project pass: package-wide "
                         "symbol index, conf-key hygiene (ZL016) and the "
                         "four code↔docs contract reconciliations "
                         "(ZL017-ZL020); exit 0 clean / 2 findings")
    ap.add_argument("--docs-root", metavar="DIR",
                    help="repository root the --contracts catalogs are "
                         "resolved under (docs/guides/*.md, docs/CONFIG.md; "
                         "default: the directory containing the scanned "
                         "package)")
    ap.add_argument("--tests-root", metavar="DIR",
                    help="tests tree for the --contracts coverage "
                         "reconciliations (ZL019's every-site-exercised "
                         "census; default: a scanned 'tests' directory, "
                         "else <docs-root>/tests when it exists)")
    ap.add_argument("--changed-only", action="store_true",
                    help="scope the per-file scan to files changed vs the "
                         "merge-base with --base (plus untracked files); "
                         "outside a git repo the full scan runs")
    ap.add_argument("--base", metavar="REF", default="main",
                    help="git ref --changed-only diffs against "
                         "(default: main)")
    ap.add_argument("--ci", action="store_true",
                    help="CI mode: per-file scan + --contracts in one "
                         "invocation, findings mirrored as JSON lines to "
                         "the results file from .zoolint.json (exit "
                         "contract 0/1/2/3) — the entry point the tier-1 "
                         "gate runs")
    ap.add_argument("--results", metavar="FILE",
                    help="(--ci) override the JSON results file")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human",
                    help="output format: human lines (default), one JSON "
                         "object per finding, or a single SARIF 2.1.0 "
                         "document for code-scanning UIs")
    ap.add_argument("--profile", action="store_true",
                    help="print per-rule wall-time to stderr after the "
                         "scan (slow rules surface before they bloat the "
                         "tier-1 gate)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every registered rule and exit")
    args = ap.parse_args(argv)

    results_path = args.results
    if args.ci:
        args.contracts = True
        cfg_path = _find_ci_config(args.paths)
        if cfg_path is not None:
            try:
                with open(cfg_path, encoding="utf-8") as f:
                    cfg = json.load(f)
            except (OSError, ValueError) as e:
                ap.error(f"cannot read {cfg_path}: {e}")
            cfg_dir = os.path.dirname(os.path.abspath(cfg_path))

            def _rel(p):
                return p if os.path.isabs(p) else os.path.join(cfg_dir, p)

            if not args.paths and cfg.get("paths"):
                args.paths = [_rel(p) for p in cfg["paths"]]
            if args.docs_root is None and cfg.get("docs_root"):
                args.docs_root = _rel(cfg["docs_root"])
            if args.tests_root is None and cfg.get("tests_root"):
                args.tests_root = _rel(cfg["tests_root"])
            if results_path is None and cfg.get("results"):
                results_path = _rel(cfg["results"])
            if args.select is None and cfg.get("select"):
                args.select = ",".join(cfg["select"])
            if args.ignore is None and cfg.get("ignore"):
                args.ignore = ",".join(cfg["ignore"])
            # a config written for a different results-file shape must
            # fail loudly, not feed CI lines it will misparse
            pinned = cfg.get("results_schema")
            if pinned is not None and pinned != RESULTS_SCHEMA:
                ap.error(f"{cfg_path} pins results_schema={pinned} but "
                         f"this zoolint writes schema {RESULTS_SCHEMA}")

    if args.list_rules:
        for rule in all_rules():
            doc = " ".join((rule.__doc__ or "").split())
            print(f"{rule.id} [{rule.severity}] {doc}")
        for rule in all_project_rules():
            doc = " ".join((rule.__doc__ or "").split())
            print(f"{rule.id} [{rule.severity}] [project] {doc}")
        return 0

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path would scan zero files and read as a green gate
        ap.error(f"path does not exist: {', '.join(missing)}")
    select, ignore = _split_ids(args.select), _split_ids(args.ignore)
    # same green-gate hazard as a typo'd path: `--select ZL0O1` would run
    # zero rules and exit 0 (ZL000 is the reserved unparseable-file id)
    known = {r.id for r in all_rules()} \
        | {r.id for r in all_project_rules()} | {"ZL000"}
    unknown = [i for i in (select or []) + (ignore or []) if i not in known]
    if unknown:
        ap.error(f"unknown rule id(s): {', '.join(unknown)} "
                 f"(see --list-rules)")
    # `--select ZL016` without --contracts would run the project-only
    # rule never: zero findings, exit 0 — the same green-gate hazard as
    # an unknown id, so fail just as loudly (--ignore stays harmless)
    if not args.contracts:
        # ZL022 registers in BOTH registries (use direction per-file,
        # declaration direction in the project pass) — only ids with no
        # per-file half are project-only
        proj_only = {r.id for r in all_project_rules()} \
            - {r.id for r in all_rules()}
        selected_proj = [i for i in (select or []) if i in proj_only]
        if selected_proj:
            ap.error(f"rule id(s) {', '.join(selected_proj)} run only "
                     f"under the project pass — add --contracts")
    paths = args.paths or default_paths()
    changed: Optional[Set[str]] = None
    if args.changed_only:
        # anchor git at the SCANNED tree, not the process cwd — a cwd in
        # an unrelated repo would otherwise scope to that repo's diff
        # and silently scan nothing
        first = os.path.abspath(paths[0])
        anchor = first if os.path.isdir(first) else os.path.dirname(first)
        changed = git_changed_files(args.base, anchor=anchor)
        if changed is None:
            print("zoolint: --changed-only outside a git repo (or git "
                  "unavailable) — running the full scan", file=sys.stderr)

    def scan_files():
        for p in iter_py_files(paths):
            if changed is None or os.path.realpath(p) in changed:
                yield p

    profile: Optional[dict] = {} if args.profile else None
    project_findings: List = []
    if not args.contracts:
        if changed is None:
            findings = lint_paths(paths, select=select, ignore=ignore,
                                  profile=profile)
        else:
            findings = []
            for path in scan_files():
                findings.extend(lint_file(path, select=select,
                                          ignore=ignore, profile=profile))
    else:
        # the contract surfaces govern SHIPPED package code: the project
        # pass indexes the scanned directories that are package roots
        # (an `__init__.py` at the top), so tests/ fixtures injecting
        # synthetic sites/metrics never pollute the reconciliation —
        # they are still covered by the per-file rules
        dirs = [p for p in paths if os.path.isdir(p)]
        pkgs = [p for p in dirs
                if os.path.isfile(os.path.join(p, "__init__.py"))]
        roots = pkgs or dirs or paths
        docs_root = args.docs_root
        if docs_root is None:
            docs_root = os.path.dirname(
                os.path.abspath(roots[0]) if roots else package_root())
        tests_root = args.tests_root
        if tests_root is None:
            named_tests = [p for p in dirs
                           if os.path.basename(
                               os.path.abspath(p)) == "tests"]
            if named_tests:
                tests_root = named_tests[0]
            elif os.path.isdir(os.path.join(docs_root, "tests")):
                tests_root = os.path.join(docs_root, "tests")
        project = ProjectContext(roots, docs_root=docs_root,
                                 tests_root=tests_root)
        # per-file rules reuse the project's already-parsed modules —
        # one parse per package file for both passes; files outside the
        # package roots (tests/, bench.py) parse normally, and a broken
        # package file falls through to lint_file so ZL000 is reported
        # exactly once, by the per-file scan. --changed-only scopes the
        # per-file half only — the contract surfaces are whole-tree by
        # construction.
        findings = []
        for path in scan_files():
            ctx = project.by_path.get(path)
            findings.extend(
                lint_context(ctx, select=select, ignore=ignore,
                             profile=profile)
                if ctx is not None
                else lint_file(path, select=select, ignore=ignore,
                               profile=profile))
        project_findings = lint_project(
            project=project, select=select, ignore=ignore,
            report_unparseable=False, profile=profile)
        findings = findings + project_findings
        findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    if args.errors_only:
        findings = [f for f in findings if f.severity == ERROR]

    def _jsonl(f) -> str:
        return json.dumps({"rule": f.rule_id, "file": f.path,
                           "line": f.line, "severity": f.severity,
                           "message": f.message}, sort_keys=True)

    if args.ci and results_path:
        # schema 2: the first line is a header object naming the rule
        # ids that RAN, so the gate can assert a pass actually executed
        # (a silently-unregistered pass previously read as a green run)
        ran = {r.id for r in all_rules()}
        if args.contracts:
            ran |= {r.id for r in all_project_rules()}
        if select is not None:
            ran &= set(select)
        ran -= set(ignore or ())
        header = json.dumps({"zoolint_results_schema": RESULTS_SCHEMA,
                             "rules": sorted(ran)}, sort_keys=True)
        try:
            with open(results_path, "w", encoding="utf-8") as out:
                out.write(header + "\n")
                for f in findings:
                    out.write(_jsonl(f) + "\n")
        except OSError as e:
            # an unwritable results file must not mask the scan verdict
            print(f"zoolint: cannot write results file "
                  f"{results_path}: {e}", file=sys.stderr)
    if args.format == "sarif":
        # one SARIF 2.1.0 document on stdout — uploadable to
        # code-scanning UIs as-is
        print(json.dumps(_sarif_doc(findings, args.contracts),
                         indent=2, sort_keys=True))
    else:
        for f in findings:
            if args.format == "json":
                print(_jsonl(f))
            else:
                print(f.format())
    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = len(findings) - errors
    n_rules = len(all_rules()) + (len(all_project_rules())
                                  if args.contracts else 0)
    summary = (f"zoolint: {errors} error(s), {warnings} warning(s), "
               f"{n_rules} rule(s)"
               + (" [contracts]" if args.contracts else ""))
    # json/sarif modes keep stdout machine-parseable
    print(summary,
          file=sys.stderr if args.format in ("json", "sarif")
          else sys.stdout)
    if profile is not None:
        # slowest first; project-pass rules keyed ZLxxx[project]
        for rid, secs in sorted(profile.items(), key=lambda kv: -kv[1]):
            print(f"zoolint-profile: {rid} {secs:.3f}s", file=sys.stderr)
    if args.contracts:
        # the exit codes stay distinguishable: 2 = the CONTRACT surface
        # drifted (project-pass findings), 1 = only per-file code
        # hazards (same meaning as the plain scan), 0 = clean
        if any(f.severity == ERROR for f in project_findings):
            return 2
    return 1 if errors else 0


if __name__ == "__main__":    # pragma: no cover
    sys.exit(main())
