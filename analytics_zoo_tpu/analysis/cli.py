"""``python -m analytics_zoo_tpu.analysis`` — the zoolint command line.

Exit status is 1 when any ERROR-severity finding survives suppression,
0 otherwise (warnings never gate). With no paths it scans the installed
``analytics_zoo_tpu`` package plus the sibling ``tests/`` directory and
``bench.py`` when they exist — exactly what the CI gate
(`tests/test_zoolint.py`) runs.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .core import ERROR, all_rules, lint_paths


def default_paths() -> List[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg)
    paths = [pkg]
    # keep in sync with tests/test_zoolint.py's gate scan — the bare CLI
    # must agree with what CI enforces
    for extra in (os.path.join(root, "tests"),
                  os.path.join(root, "bench.py")):
        if os.path.exists(extra):
            paths.append(extra)
    return paths


def _split_ids(value: Optional[str]) -> Optional[List[str]]:
    if not value:
        return None
    return [v.strip() for v in value.split(",") if v.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="zoolint",
        description="JAX/TPU-aware static analysis for analytics_zoo_tpu "
                    "(PRNG reuse, host effects under jit, hidden syncs, "
                    "import-time device init, ...)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to scan (default: the "
                         "analytics_zoo_tpu package, tests/ and bench.py)")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", metavar="IDS",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--errors-only", action="store_true",
                    help="print (and count) only error-severity findings")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every registered rule and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            doc = " ".join((rule.__doc__ or "").split())
            print(f"{rule.id} [{rule.severity}] {doc}")
        return 0

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path would scan zero files and read as a green gate
        ap.error(f"path does not exist: {', '.join(missing)}")
    select, ignore = _split_ids(args.select), _split_ids(args.ignore)
    # same green-gate hazard as a typo'd path: `--select ZL0O1` would run
    # zero rules and exit 0 (ZL000 is the reserved unparseable-file id)
    known = {r.id for r in all_rules()} | {"ZL000"}
    unknown = [i for i in (select or []) + (ignore or []) if i not in known]
    if unknown:
        ap.error(f"unknown rule id(s): {', '.join(unknown)} "
                 f"(see --list-rules)")
    findings = lint_paths(args.paths or default_paths(),
                          select=select, ignore=ignore)
    if args.errors_only:
        findings = [f for f in findings if f.severity == ERROR]
    for f in findings:
        print(f.format())
    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = len(findings) - errors
    print(f"zoolint: {errors} error(s), {warnings} warning(s), "
          f"{len(all_rules())} rule(s)")
    return 1 if errors else 0


if __name__ == "__main__":    # pragma: no cover
    sys.exit(main())
