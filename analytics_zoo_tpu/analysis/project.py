"""zoolint project pass — whole-tree analysis on top of the per-file
rules.

The per-file rules (``rules.py``) see one module at a time; this second
stage parses the WHOLE package into a :class:`ProjectContext` — every
module's :class:`~.core.ModuleContext` plus a package-wide,
import-resolved symbol index — and runs **project rules** against it:
checks that structurally cannot be per-file, like "is this conf key
read anywhere" (ZL016) or "does every metric registration have a
catalog row" (the contract reconciliations in ``contracts.py``,
ZL017–ZL020).

The symbol index maps, for every module, each local name to the
fully-qualified symbol it was imported as (relative imports resolved
against the module's own dotted path), and each dotted module name to
its context and top-level bindings. Rules use it to answer "what does
``faults`` refer to in this file" without guessing from spelling.

Entry points: :func:`lint_project` (in-process) and the CLI's
``--contracts`` flag (exit 0 clean / 2 findings). Suppression works
like the per-file pass: findings anchored in a ``.py`` file honor
``# zoolint: disable=ZLxxx`` on their line (or the first line of the
enclosing multi-line statement); findings anchored in a catalog ``.md``
file are not suppressible — fix the doc instead.
"""

from __future__ import annotations

import ast
import os
import time
from typing import Dict, Iterable, Iterator, List, Optional, Set

from .core import (ERROR, Finding, ModuleContext, iter_py_files)


class ProjectRule:
    """One whole-project check. Like :class:`~.core.Rule` but
    :meth:`check` receives the :class:`ProjectContext`."""

    id: str = ""
    severity: str = ERROR

    def check(self, project: "ProjectContext") -> Iterator[Finding]:
        raise NotImplementedError


_PROJECT_REGISTRY: Dict[str, "ProjectRule"] = {}


def register_project(cls):
    """Class decorator adding one project rule to the registry."""
    if not cls.id:
        raise ValueError(f"{cls.__name__} has no id")
    if cls.id in _PROJECT_REGISTRY:
        raise ValueError(f"duplicate project rule id {cls.id}")
    _PROJECT_REGISTRY[cls.id] = cls()
    return cls


def all_project_rules() -> List[ProjectRule]:
    from . import contracts  # noqa: F401  (registers on first import)
    from . import device  # noqa: F401  (ZL022's declaration direction)
    from . import spmd  # noqa: F401  (ZL025's collective-catalog half)
    return sorted(_PROJECT_REGISTRY.values(), key=lambda r: r.id)


class ProjectContext:
    """Every parsed module of a package tree + the shared cross-file
    facts project rules query."""

    def __init__(self, paths: Iterable[str],
                 docs_root: Optional[str] = None,
                 tests_root: Optional[str] = None):
        self.docs_root = docs_root
        #: tests tree for the coverage reconciliations (ZL019's
        #: site-census direction); None = those checks stay off
        self.tests_root = tests_root
        self._tests_census: Optional[Set[str]] = None
        self.modules: List[ModuleContext] = []
        self.by_path: Dict[str, ModuleContext] = {}
        self.by_name: Dict[str, ModuleContext] = {}
        #: files the project pass could not parse (reported as ZL000)
        self.unparseable: List[Finding] = []
        self._mod_name: Dict[str, str] = {}      # path -> dotted module
        self._imports: Dict[str, Dict[str, str]] = {}   # path -> local->fq
        roots = list(paths)
        for path in iter_py_files(roots):
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                ctx = ModuleContext(path, source)
            except (OSError, UnicodeDecodeError, SyntaxError, ValueError) \
                    as e:
                self.unparseable.append(Finding(
                    "ZL000", ERROR, path, getattr(e, "lineno", 1) or 1,
                    f"project pass cannot parse: "
                    f"{getattr(e, 'msg', None) or e}"))
                continue
            self.modules.append(ctx)
            self.by_path[path] = ctx
            name = self._derive_module_name(path)
            self._mod_name[path] = name
            self.by_name[name] = ctx

    # -- module naming ------------------------------------------------------
    @staticmethod
    def _derive_module_name(path: str) -> str:
        """Dotted module name: walk up from the file through every
        directory that carries an ``__init__.py`` — the package spine —
        so the name matches what an importer would bind regardless of
        which root the scan started from."""
        apath = os.path.abspath(path)
        parts = [os.path.splitext(os.path.basename(apath))[0]]
        d = os.path.dirname(apath)
        while os.path.isfile(os.path.join(d, "__init__.py")):
            parts.append(os.path.basename(d))
            nd = os.path.dirname(d)
            if nd == d:
                break
            d = nd
        name = ".".join(reversed(parts))
        return name[:-len(".__init__")] if name.endswith(".__init__") \
            else name

    def module_name(self, ctx: ModuleContext) -> str:
        return self._mod_name.get(ctx.path,
                                  os.path.splitext(
                                      os.path.basename(ctx.path))[0])

    # -- import-resolved symbol index ---------------------------------------
    def imports(self, ctx: ModuleContext) -> Dict[str, str]:
        """``local name -> fully-qualified imported symbol`` for one
        module, with relative imports resolved against the module's own
        dotted path (``from ..common import faults`` inside
        ``analytics_zoo_tpu.serving.server`` resolves to
        ``analytics_zoo_tpu.common.faults``)."""
        cached = self._imports.get(ctx.path)
        if cached is not None:
            return cached
        mod = self.module_name(ctx)
        # the package a relative import is anchored at: the module's
        # parent for a plain module, the module itself for __init__
        is_pkg = os.path.basename(ctx.path) == "__init__.py"
        pkg_parts = mod.split(".") if is_pkg else mod.split(".")[:-1]
        out: Dict[str, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        out[a.asname] = a.name
                    else:
                        top = a.name.split(".", 1)[0]
                        out[top] = top
            elif isinstance(node, ast.ImportFrom):
                base: Optional[str]
                if node.level:
                    up = node.level - 1
                    if up > len(pkg_parts):
                        base = None     # beyond the scanned tree's root
                    else:
                        anchor = pkg_parts[:len(pkg_parts) - up]
                        base = ".".join(
                            anchor + ([node.module] if node.module
                                      else []))
                else:
                    base = node.module
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = f"{base}.{a.name}"
        self._imports[ctx.path] = out
        return out

    def resolve(self, ctx: ModuleContext, name: str) -> Optional[str]:
        """The fully-qualified symbol a (possibly dotted) local name
        refers to in ``ctx``, or None when it is not import-bound (a
        local def/assignment or a builtin)."""
        head, _, rest = name.partition(".")
        fq = self.imports(ctx).get(head)
        if fq is None:
            return None
        return f"{fq}.{rest}" if rest else fq

    def catalog_path(self, surface: str) -> Optional[str]:
        from .contracts import find_catalog
        if self.docs_root is None:
            return None
        return find_catalog(self.docs_root, surface)

    # -- tests-tree string census -------------------------------------------
    def tests_string_census(self) -> Optional[Set[str]]:
        """Every exact string constant appearing anywhere in the parsed
        ``tests_root`` tree — the coverage census ZL019 reconciles fault
        sites against (a site exercised by a chaos plan necessarily
        spells its name as a string in some test). None when no tests
        root was configured; a broken test file is skipped (pytest
        fails it far more loudly than a census could)."""
        if self.tests_root is None:
            return None
        if self._tests_census is None:
            census: Set[str] = set()
            for path in iter_py_files([self.tests_root]):
                try:
                    with open(path, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=path)
                except (OSError, UnicodeDecodeError, SyntaxError,
                        ValueError):
                    continue
                for node in ast.walk(tree):
                    if isinstance(node, ast.Constant) \
                            and isinstance(node.value, str):
                        census.add(node.value)
            self._tests_census = census
        return self._tests_census


def lint_project(paths: Optional[Iterable[str]] = None,
                 docs_root: Optional[str] = None,
                 tests_root: Optional[str] = None,
                 select: Optional[Iterable[str]] = None,
                 ignore: Optional[Iterable[str]] = None,
                 project: Optional["ProjectContext"] = None,
                 report_unparseable: bool = True,
                 profile: Optional[Dict[str, float]] = None
                 ) -> List[Finding]:
    """Run every project rule over the package tree rooted at ``paths``
    (or a prebuilt ``project`` — the CLI reuses one so files parse once
    for both passes); returns non-suppressed findings, sorted by
    path/line/rule. ``tests_root`` switches on the test-coverage
    reconciliations (ZL019's site census). ``report_unparseable=False``
    drops the project pass's own ZL000 findings — for callers whose
    per-file scan already reported the same broken files. ``profile``
    accumulates per-rule wall-clock seconds (keyed ``ZLxxx[project]``
    so the two ZL022/ZL025 halves stay distinguishable)."""
    if project is None:
        if paths is None:
            raise ValueError("lint_project needs paths or a project")
        project = ProjectContext(paths, docs_root=docs_root,
                                 tests_root=tests_root)
    select_set = set(select) if select else None
    ignore_set = set(ignore) if ignore else set()
    out: List[Finding] = []
    if report_unparseable and "ZL000" not in ignore_set and (
            select_set is None or "ZL000" in select_set):
        out.extend(project.unparseable)
    seen: Set = set()
    for rule in all_project_rules():
        if select_set is not None and rule.id not in select_set:
            continue
        if rule.id in ignore_set:
            continue
        t0 = time.perf_counter() if profile is not None else 0.0
        found = list(rule.check(project))
        if profile is not None:
            key = f"{rule.id}[project]"
            profile[key] = profile.get(key, 0.0) \
                + (time.perf_counter() - t0)
        for f in found:
            key = (f.rule_id, f.path, f.line, f.message)
            if key in seen:
                continue
            seen.add(key)
            ctx = project.by_path.get(f.path)
            if ctx is not None and ctx.suppressed(f):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return out
