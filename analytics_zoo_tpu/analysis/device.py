"""zoolint device-semantics pass — rules ZL021–ZL024.

The per-file rules in ``rules.py`` flag *structural* staged-computation
hazards (host effects, traced branches, missing donation). This third
stage adds a lightweight **abstract interpreter** over jit-staged and
Pallas-kernel code: a straight-line walk that tracks constant-foldable
integers, dtypes, tile-alignment facts and ``pad_to_multiple`` padding
through assignments and this codebase's known call idioms (``round_up``,
``min``/``max`` clamps, ``x // m * m`` floors, local helper calls one
level deep). On top of it:

* **ZL021** — dtype-promotion hazards in staged bodies: explicit float64
  dtype introductions (silently truncated under TPU x64-off), bf16/fp16
  reductions and MXU dots without an explicit f32 accumulation, and
  ``lax.scan`` carries initialized in a 16-bit dtype yet accumulated
  into (the fused-CE f32-carry discipline, generalized).
* **ZL022** — mesh-axis discipline: every axis name appearing in a
  ``PartitionSpec``/collective must come from the declared axis
  vocabulary extracted from the package's mesh module
  (``parallel/mesh.py``) or an in-file ``Mesh(...)`` construction; the
  project pass adds the reverse direction (declared-but-never-used
  axes, warning severity).
* **ZL023** — Pallas tile alignment: block-shape dims in ``BlockSpec``/
  ``pltpu.VMEM`` must be *provably* on the LANES/SUBLANES tile floors —
  ``round_up``-wrapped expressions, ``// m * m`` floors and
  already-aligned constants prove out; a raw ``min()`` clamp that can
  land off the floor is exactly the Mosaic-only-fails-on-TPU bug class
  PR 8's review caught by hand.
* **ZL024** — static VMEM budget: a provable LOWER bound on a
  ``pallas_call``'s double-buffered operand windows + outputs + scratch
  is priced with the **same footprint estimator the runtime autotuner
  uses** (``ops/pallas/common.kernel_vmem_bytes``, loaded standalone —
  no jax import) against the 16 MiB per-core default; a kernel that
  provably cannot fit fails lint instead of a TPU run.

The estimator module is loaded straight off ``ops/pallas/common.py``
with ``importlib`` (no package ``__init__`` chain, so the linter stays
jax-free); when the file is missing (linting a foreign tree) the
tile-floor constants fall back to the hardware values and ZL024 skips.

The fourth stage (``spmd.py``, ZL025–ZL028) builds on this module: it
reuses the collective-call table (``_COLLECTIVES``), the axis-name
folding and mesh-vocabulary extraction (``_fold_axis_names``,
``extract_axis_decls``, ``package_axis_vocabulary``) and the
staged-region discovery (``staged_fns``) — changes to those helpers
are shared contract surface for both passes.
"""

from __future__ import annotations

import ast
import dataclasses
import math
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import (ERROR, WARNING, Finding, ModuleContext, Rule, dotted,
                   register)
from .project import ProjectContext, ProjectRule, register_project

# ---------------------------------------------------------------------------
# the shared footprint estimator (ops/pallas/common.py), loaded standalone
# ---------------------------------------------------------------------------

_FALLBACK_LANES = 128
_FALLBACK_SUBLANES = 8
_common_mod = None
_common_tried = False


def footprint_module():
    """The live ``ops/pallas/common.py`` module — the SAME estimator the
    runtime autotuner prices blocks with — loaded standalone so no jax
    (or package ``__init__``) import happens. None when unavailable."""
    global _common_mod, _common_tried
    if _common_tried:
        return _common_mod
    _common_tried = True
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ops", "pallas", "common.py")
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_zoolint_pallas_common", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _common_mod = mod
    # a missing/broken estimator degrades ZL024 to a skip — the per-file
    # alignment rules keep running on the fallback tile constants
    except Exception:  # zoolint: disable=ZL007
        _common_mod = None
    return _common_mod


def _tile_floors() -> Tuple[int, int]:
    mod = footprint_module()
    if mod is not None:
        return int(mod.LANES), int(mod.SUBLANES)
    return _FALLBACK_LANES, _FALLBACK_SUBLANES


# ---------------------------------------------------------------------------
# dtype resolution
# ---------------------------------------------------------------------------

_F64 = {"float64"}
_F16 = {"bfloat16", "float16"}
_CANON = {"double": "float64", "half": "float16", "single": "float32"}
_DTYPE_LEAVES = {"float64", "double", "float32", "single", "bfloat16",
                 "float16", "half", "int8", "int16", "int32", "int64",
                 "uint8", "uint16", "uint32", "uint64", "bool_",
                 "complex64", "complex128"}
_ITEMSIZE = {"float64": 8, "complex64": 8, "complex128": 16, "int64": 8,
             "uint64": 8, "float32": 4, "int32": 4, "uint32": 4,
             "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
             "int8": 1, "uint8": 1, "bool_": 1}


def dtype_of_node(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
    """The canonical dtype a dtype-denoting expression names:
    ``jnp.float64`` / ``np.bfloat16`` / ``"float64"`` string literals /
    names from-imported off numpy or jax.numpy. None when the expression
    is not a recognizable dtype."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        leaf = node.value
        if leaf in _DTYPE_LEAVES:
            return _CANON.get(leaf, leaf)
        return None
    d = dotted(node)
    if not d:
        return None
    if "." in d:
        prefix, leaf = d.rsplit(".", 1)
        if leaf in _DTYPE_LEAVES and (
                prefix in ctx.aliases.get("numpy", ())
                or prefix in ctx.aliases.get("jax.numpy", ())):
            return _CANON.get(leaf, leaf)
        return None
    for mod in ("numpy", "jax.numpy"):
        orig = ctx.from_imported(mod).get(d)
        if orig in _DTYPE_LEAVES:
            return _CANON.get(orig, orig)
    return None


# ---------------------------------------------------------------------------
# the abstract interpreter
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Abs:
    """One abstract value: what the interpreter could prove about an
    expression. ``align`` is a divisor the value is provably a multiple
    of; ``low`` a provable positive lower bound; ``clamped`` marks a
    ``min()``-style derivation whose result may have left the tile floor
    (cleared again by ``round_up``/``// m * m``); ``from_shape`` marks a
    dim pulled straight off an array's ``.shape`` (a whole-axis block
    dim, which Mosaic pads — exempt from alignment proofs); ``pads``
    carries ``pad_to_multiple`` facts (axis -> multiple) on arrays."""

    const: Optional[int] = None
    dtype: Optional[str] = None
    align: int = 1
    low: int = 1
    clamped: bool = False
    from_shape: bool = False
    pads: Optional[Dict[int, int]] = None
    elts: Optional[List["Abs"]] = None      # tuple values (returns, literals)

    @staticmethod
    def of_const(v: int) -> "Abs":
        return Abs(const=v, align=max(abs(v), 1), low=max(v, 1))


_REDUCERS = ("sum", "mean", "prod", "cumsum", "cumprod")
_DOTS = ("dot", "matmul", "dot_general", "tensordot")


class Interp:
    """Straight-line abstract interpretation of one function (or the
    module top level): a forward statement walk building ``name -> Abs``.
    Branch arms apply in order (the join is last-writer-wins — fine for
    *proofs*: a fact is only used to prove alignment/dtype, and an
    over-written fact merely loses precision). Local helper calls
    resolve one level deep so ``_prep``-style tuple returns carry their
    alignment facts to the caller."""

    def __init__(self, ctx: ModuleContext, depth: int = 0):
        self.ctx = ctx
        self.depth = depth
        self._module_env: Optional[Dict[str, Abs]] = None
        # names import-bound to the hardware tile constants — cached ON
        # the context: three rules and every resolved helper call build
        # an Interp, and re-walking the tree per instance is O(calls ×
        # tree) for a fact that never changes
        cached = getattr(ctx, "_zl_tile_names", None)
        if cached is None:
            cached = {}
            lanes, sublanes = _tile_floors()
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ImportFrom):
                    for a in node.names:
                        if a.name == "LANES":
                            cached[a.asname or a.name] = lanes
                        elif a.name == "SUBLANES":
                            cached[a.asname or a.name] = sublanes
            ctx._zl_tile_names = cached  # type: ignore[attr-defined]
        self._tile_names: Dict[str, int] = cached

    # -- environments -------------------------------------------------------
    def module_env(self) -> Dict[str, Abs]:
        if self._module_env is None:
            self._module_env = {}
            self._walk_stmts(self.ctx.tree.body, self._module_env)
        return self._module_env

    def env_of(self, fn: ast.AST) -> Dict[str, Abs]:
        env: Dict[str, Abs] = {}
        body = fn.body if not isinstance(fn, ast.Lambda) else []
        self._walk_stmts(body, env)
        return env

    def _walk_stmts(self, stmts, env: Dict[str, Abs]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue        # separate scope
            if isinstance(stmt, ast.Assign):
                self._assign(stmt.targets, stmt.value, env)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._assign([stmt.target], stmt.value, env)
            elif isinstance(stmt, ast.AugAssign) and \
                    isinstance(stmt.target, ast.Name):
                synth = ast.BinOp(left=ast.Name(id=stmt.target.id,
                                                ctx=ast.Load()),
                                  op=stmt.op, right=stmt.value)
                self._bind(env, stmt.target.id, self._binop_abs(
                    stmt.op, self.eval(synth.left, env),
                    self.eval(stmt.value, env)))
            elif isinstance(stmt, (ast.If, ast.For, ast.AsyncFor,
                                   ast.While)):
                self._walk_stmts(stmt.body, env)
                self._walk_stmts(stmt.orelse, env)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._walk_stmts(stmt.body, env)
            elif isinstance(stmt, ast.Try):
                self._walk_stmts(stmt.body, env)
                for h in stmt.handlers:
                    self._walk_stmts(h.body, env)
                self._walk_stmts(stmt.finalbody, env)

    @staticmethod
    def _bind(env: Dict[str, Abs], name: str, val: Abs) -> None:
        """Bind with a dtype-conflict demotion: the env is last-writer-
        wins (flow-insensitive), which is fine for *proofs* but not for
        *accusations* — ZL021 flags on a tracked 16-bit dtype, and a
        name rebound f32-then-bf16 must not retroactively accuse the
        earlier f32 use. Two CONCRETE, different dtypes on one name
        demote it to unknown; everything else keeps the last writer."""
        old = env.get(name)
        if old is not None and old.dtype and val.dtype \
                and old.dtype != val.dtype:
            val = dataclasses.replace(val, dtype=None)
        env[name] = val

    def _assign(self, targets, value, env: Dict[str, Abs]) -> None:
        val = self.eval(value, env)
        for t in targets:
            if isinstance(t, ast.Name):
                self._bind(env, t.id, val)
            elif isinstance(t, (ast.Tuple, ast.List)):
                if val.elts is not None and len(val.elts) == len(t.elts):
                    for sub, sv in zip(t.elts, val.elts):
                        if isinstance(sub, ast.Name):
                            self._bind(env, sub.id, sv)
                elif self._is_shape_expr(value):
                    pads = val.pads or {}
                    for i, sub in enumerate(t.elts):
                        if isinstance(sub, ast.Name):
                            env[sub.id] = Abs(from_shape=True,
                                              align=pads.get(i, 1),
                                              low=max(pads.get(i, 1), 1))

    @staticmethod
    def _is_shape_expr(node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and node.attr == "shape"

    # -- expression evaluation ----------------------------------------------
    def eval(self, node: ast.AST, env: Dict[str, Abs]) -> Abs:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return Abs()
            if isinstance(node.value, int):
                return Abs.of_const(node.value)
            if isinstance(node.value, float):
                return Abs()
            return Abs()
        if isinstance(node, ast.Name):
            if node.id in self._tile_names:
                return Abs.of_const(self._tile_names[node.id])
            if node.id in env:
                return env[node.id]
            return self.module_env().get(node.id, Abs())
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d and d.split(".")[-1] in self._tile_names:
                return Abs.of_const(self._tile_names[d.split(".")[-1]])
            # module-constant via alias (mesh_lib.LANES-style) stays
            # unresolved here; dtype leaves are handled by dtype_of_node
            return Abs()
        if isinstance(node, ast.BinOp):
            return self._binop_abs(node.op, self.eval(node.left, env),
                                   self.eval(node.right, env),
                                   node=node, env=env)
        if isinstance(node, ast.UnaryOp):
            inner = self.eval(node.operand, env)
            if isinstance(node.op, ast.USub) and inner.const is not None:
                return Abs.of_const(-inner.const)
            return Abs(dtype=inner.dtype)
        if isinstance(node, ast.IfExp):
            a = self.eval(node.body, env)
            b = self.eval(node.orelse, env)
            return self._join(a, b)
        if isinstance(node, (ast.Tuple, ast.List)):
            return Abs(elts=[self.eval(e, env) for e in node.elts])
        if isinstance(node, ast.Subscript):
            base = node.value
            if self._is_shape_expr(base):
                arr = self.eval(base.value, env)
                idx = self.eval(node.slice, env)
                pads = arr.pads or {}
                if idx.const is not None and idx.const in pads:
                    m = pads[idx.const]
                    return Abs(from_shape=True, align=m, low=m)
                return Abs(from_shape=True)
            seq = self.eval(base, env)
            idx = self.eval(node.slice, env)
            if seq.elts is not None and idx.const is not None \
                    and 0 <= idx.const < len(seq.elts):
                return seq.elts[idx.const]
            return Abs()
        if isinstance(node, ast.Call):
            return self._call_abs(node, env)
        return Abs()

    def _join(self, a: Abs, b: Abs) -> Abs:
        return Abs(const=a.const if a.const == b.const else None,
                   dtype=a.dtype if a.dtype == b.dtype else None,
                   align=math.gcd(a.align, b.align) or 1,
                   low=min(a.low, b.low),
                   clamped=a.clamped or b.clamped,
                   from_shape=a.from_shape and b.from_shape)

    def _binop_abs(self, op, a: Abs, b: Abs, node=None, env=None) -> Abs:
        dtype = self._promote(a.dtype, b.dtype)
        if isinstance(op, (ast.Add, ast.Sub)):
            const = None
            if a.const is not None and b.const is not None:
                const = a.const + b.const if isinstance(op, ast.Add) \
                    else a.const - b.const
            out = Abs(const=const, dtype=dtype,
                      align=math.gcd(a.align, b.align) or 1,
                      clamped=a.clamped or b.clamped)
            if isinstance(op, ast.Add):
                out.low = a.low + b.low
            if const is not None:
                out.align = max(abs(const), 1)
                out.low = max(const, 1)
            return out
        if isinstance(op, ast.Mult):
            # the `x // m * m` floor pattern proves alignment to m
            if node is not None and isinstance(node.left, ast.BinOp) \
                    and isinstance(node.left.op, ast.FloorDiv) \
                    and b.const is not None and b.const > 0:
                return Abs(align=b.const, low=b.const, dtype=dtype)
            const = None
            if a.const is not None and b.const is not None:
                const = a.const * b.const
            return Abs(const=const, dtype=dtype,
                       align=max(a.align * b.align, 1),
                       low=max(a.low * b.low, 1),
                       clamped=a.clamped or b.clamped)
        if isinstance(op, ast.FloorDiv):
            if a.const is not None and b.const is not None and b.const:
                return Abs.of_const(a.const // b.const)
            # a bare floor-div is the block-halving hazard until a
            # `* m` / round_up re-floors it
            return Abs(clamped=True, dtype=dtype)
        if isinstance(op, ast.Mod):
            if a.const is not None and b.const is not None and b.const:
                return Abs.of_const(a.const % b.const)
            return Abs(dtype=dtype)
        return Abs(dtype=dtype)

    @staticmethod
    def _promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
        if a == b:
            return a
        if a in _F16 and b in ("float32", "float64"):
            return b
        if b in _F16 and a in ("float32", "float64"):
            return a
        return None

    # -- calls ---------------------------------------------------------------
    def _call_abs(self, node: ast.Call, env: Dict[str, Abs]) -> Abs:
        d = dotted(node.func)
        leaf = d.split(".")[-1] if d else None
        args = [self.eval(a, env) for a in node.args
                if not isinstance(a, ast.Starred)]
        kw = {k.arg: k.value for k in node.keywords if k.arg}

        if leaf in ("round_up", "_round_up") and len(args) >= 2 \
                and args[1].const is not None and args[1].const > 0:
            m = args[1].const
            const = None
            if args[0].const is not None:
                const = -(-args[0].const // m) * m
            return Abs(const=const, align=m if const is None
                       else max(const, 1), low=max(m, 1))
        if leaf == "min" and "." not in (d or "") and args:
            consts = [a.const for a in args]
            if all(c is not None for c in consts):
                return Abs.of_const(min(consts))
            return Abs(align=math.gcd(*[a.align for a in args])
                       if len(args) > 1 else args[0].align,
                       low=min(a.low for a in args),
                       clamped=True,
                       dtype=args[0].dtype if len(args) == 1 else None)
        if leaf == "max" and "." not in (d or "") and args:
            consts = [a.const for a in args]
            if all(c is not None for c in consts):
                return Abs.of_const(max(consts))
            return Abs(align=math.gcd(*[a.align for a in args])
                       if len(args) > 1 else args[0].align,
                       low=max(a.low for a in args),
                       clamped=any(a.clamped for a in args))
        if leaf == "pad_to_multiple" and len(node.args) >= 3:
            base = args[0]
            axis, mult = args[1], args[2]
            pads = dict(base.pads or {})
            if axis.const is not None and mult.const is not None:
                pads[axis.const] = mult.const
            return Abs(dtype=base.dtype, pads=pads or None)
        if leaf == "astype" and isinstance(node.func, ast.Attribute) \
                and node.args:
            dt = dtype_of_node(self.ctx, node.args[0])
            recv = self.eval(node.func.value, env)
            return Abs(dtype=dt or recv.dtype, pads=recv.pads,
                       from_shape=recv.from_shape)
        if leaf in ("reshape", "transpose", "swapaxes", "ravel"):
            recv = self.eval(node.func.value, env) \
                if isinstance(node.func, ast.Attribute) else Abs()
            return Abs(dtype=recv.dtype)
        # dtype-introducing array constructors
        if leaf in ("zeros", "ones", "full", "empty", "asarray", "array",
                    "arange", "zeros_like", "ones_like", "full_like"):
            dt = None
            if "dtype" in kw:
                dt = dtype_of_node(self.ctx, kw["dtype"])
            elif node.args:
                for cand in node.args[1:]:
                    dt = dtype_of_node(self.ctx, cand)
                    if dt:
                        break
            return Abs(dtype=dt)
        if leaf in _DOTS and "preferred_element_type" in kw:
            return Abs(dtype=dtype_of_node(self.ctx,
                                           kw["preferred_element_type"]))
        if leaf in _REDUCERS and "dtype" in kw:
            return Abs(dtype=dtype_of_node(self.ctx, kw["dtype"]))
        # a dtype-object call like np.float64(x) yields that dtype
        dt = dtype_of_node(self.ctx, node.func)
        if dt is not None:
            return Abs(dtype=dt)
        # one level of local-helper resolution: tuple returns carry
        # their alignment facts to the caller's unpack (_prep-style)
        if self.depth < 1 and isinstance(node.func, ast.Name):
            fn = self.ctx._resolve_local_fn(node, node.func.id)
            if fn is not None and not isinstance(fn, ast.Lambda):
                return self._eval_callee(fn, node, env)
        return Abs()

    def _eval_callee(self, fn, call: ast.Call,
                     env: Dict[str, Abs]) -> Abs:
        sub = Interp(self.ctx, depth=self.depth + 1)
        sub._module_env = self._module_env
        cenv: Dict[str, Abs] = {}
        params = [p.arg for p in list(fn.args.posonlyargs)
                  + list(fn.args.args)]
        # defaults right-align onto the positional params
        defaults = fn.args.defaults
        for name, dflt in zip(params[len(params) - len(defaults):],
                              defaults):
            cenv[name] = self.eval(dflt, env)
        for name, arg in zip(params, call.args):
            if not isinstance(arg, ast.Starred):
                cenv[name] = self.eval(arg, env)
        for k in call.keywords:
            if k.arg in params:
                cenv[k.arg] = self.eval(k.value, env)
        sub._walk_stmts(fn.body, cenv)
        rets = [n for n in ast.walk(fn) if isinstance(n, ast.Return)
                and n.value is not None
                and not self.ctx.in_nested_scope(n, fn)]
        out: Optional[Abs] = None
        for r in rets:
            val = sub.eval(r.value, cenv)
            out = val if out is None else self._join_ret(out, val)
        return out or Abs()

    def _join_ret(self, a: Abs, b: Abs) -> Abs:
        if a.elts is not None and b.elts is not None \
                and len(a.elts) == len(b.elts):
            return Abs(elts=[self._join(x, y)
                             for x, y in zip(a.elts, b.elts)])
        return self._join(a, b)


# ---------------------------------------------------------------------------
# staged-function discovery (jit + scan bodies + pallas kernels)
# ---------------------------------------------------------------------------

def _pallas_names(ctx: ModuleContext
                  ) -> Tuple[Set[str], Set[str], Dict[str, str]]:
    """``(pallas_prefixes, tpu_prefixes, bare)`` — local names bound to
    the ``jax.experimental.pallas`` module, its ``tpu`` submodule, and
    ``local name -> original`` for bare from-imports of
    ``BlockSpec``/``pallas_call``/``VMEM``."""
    pallas: Set[str] = set()
    tpu: Set[str] = set()
    bare: Dict[str, str] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.experimental.pallas":
                    pallas.add(a.asname or "jax.experimental.pallas")
                elif a.name == "jax.experimental.pallas.tpu":
                    tpu.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "jax.experimental":
                for a in node.names:
                    if a.name == "pallas":
                        pallas.add(a.asname or a.name)
            elif node.module == "jax.experimental.pallas":
                for a in node.names:
                    if a.name == "tpu":
                        tpu.add(a.asname or a.name)
                    elif a.name in ("BlockSpec", "pallas_call"):
                        bare[a.asname or a.name] = a.name
            elif node.module == "jax.experimental.pallas.tpu":
                for a in node.names:
                    if a.name == "VMEM":
                        bare[a.asname or a.name] = a.name
    return pallas, tpu, bare


def _is_pallas_attr(ctx: ModuleContext, node: ast.AST,
                    leafs: Tuple[str, ...]) -> bool:
    pallas, tpu, bare = _pallas_cached(ctx)
    d = dotted(node)
    if not d:
        return False
    if "." in d:
        prefix, leaf = d.rsplit(".", 1)
        return leaf in leafs and (prefix in pallas or prefix in tpu)
    return bare.get(d) in leafs


def _pallas_cached(ctx: ModuleContext):
    # cached ON the context — an id()-keyed global dict would hand a
    # recycled id the previous module's aliases after GC
    got = getattr(ctx, "_zl_pallas_names", None)
    if got is None:
        got = _pallas_names(ctx)
        ctx._zl_pallas_names = got  # type: ignore[attr-defined]
    return got


def uses_pallas(ctx: ModuleContext) -> bool:
    pallas, tpu, bare = _pallas_cached(ctx)
    return bool(pallas or tpu or bare)


def pallas_kernel_fns(ctx: ModuleContext) -> List[ast.AST]:
    """Functions handed to ``pl.pallas_call`` — directly or through
    ``functools.partial(kernel, ...)``."""
    out: List[ast.AST] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _is_pallas_attr(ctx, node.func, ("pallas_call",))):
            continue
        if not node.args:
            continue
        target = node.args[0]
        if isinstance(target, ast.Call):        # functools.partial(kernel,..)
            d = dotted(target.func)
            if d and d.split(".")[-1] == "partial" and target.args:
                target = target.args[0]
        if isinstance(target, ast.Name):
            fn = ctx._resolve_local_fn(node, target.id)
            if fn is not None:
                out.append(fn)
    return out


def staged_fns(ctx: ModuleContext) -> List[ast.AST]:
    """Every function whose body runs on-device: jit-staged, scan-family
    bodies, and pallas kernels."""
    seen: Set[int] = set()
    out: List[ast.AST] = []
    for info in ctx.jitted.values():
        if id(info.fn) not in seen:
            seen.add(id(info.fn))
            out.append(info.fn)
    for fn in list(ctx.scan_bodies) + pallas_kernel_fns(ctx):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)
    return out


def _in_package(path: str) -> bool:
    if os.path.exists(path):
        path = os.path.abspath(path)
    p = path.replace("\\", "/")
    return "/analytics_zoo_tpu/" in p or p.startswith("analytics_zoo_tpu/")


# ---------------------------------------------------------------------------
# ZL021 — dtype-promotion hazards in staged bodies
# ---------------------------------------------------------------------------

#: call positions that INTRODUCE a dtype (a comparison like
#: ``x.dtype == jnp.float64`` is a guard, not an introduction)
_DTYPE_CTORS = ("zeros", "ones", "full", "empty", "asarray", "array",
                "arange", "zeros_like", "ones_like", "full_like",
                "astype", "convert_element_type")


@register
class DtypePromotionHazard(Rule):
    """Dtype-promotion hazards inside jit-staged / scan / pallas-kernel
    bodies: (1) an explicit **float64** introduction — under the TPU
    default (x64 off) jax silently truncates it to float32, and with
    ``jax_enable_x64`` the MXU runs it at a fraction of rate; (2) a
    **bf16/fp16 reduction or MXU dot without f32 accumulation** — the
    sum accumulates in the 16-bit type and loses mass at long-context
    lengths (pass ``dtype=jnp.float32`` / ``preferred_element_type``);
    (3) a **16-bit ``lax.scan`` carry that is accumulated into** — the
    fused-CE discipline is an f32 carry (``jnp.zeros(..., jnp.float32)``
    or ``.astype(jnp.float32)`` on the init) rounded once after the
    scan. Error in package code, warning elsewhere."""

    id = "ZL021"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        sev = ERROR if _in_package(ctx.path) else WARNING
        interp = Interp(ctx)
        for fn in staged_fns(ctx):
            env = interp.env_of(fn)
            yield from self._scan_body_nodes(ctx, interp, fn, env, sev)
        yield from self._scan_carries(ctx, interp, sev)

    # -- (1) float64 introductions + (2) 16-bit accumulation ----------------
    def _scan_body_nodes(self, ctx, interp, fn, env, sev):
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                leaf = d.split(".")[-1] if d else None
                kw = {k.arg: k.value for k in node.keywords if k.arg}
                # float64 introduction
                intro: Optional[ast.AST] = None
                if "dtype" in kw:
                    intro = kw["dtype"]
                elif leaf == "astype" and node.args:
                    intro = node.args[0]
                elif leaf in _DTYPE_CTORS and len(node.args) >= 2:
                    intro = node.args[-1]
                elif dtype_of_node(ctx, node.func) in _F64 and node.args:
                    intro = node.func      # np.float64(x) constructor
                if intro is not None and dtype_of_node(ctx, intro) in _F64:
                    yield self.finding(
                        ctx, node.lineno,
                        "float64 introduced in a jit-staged body: under "
                        "the TPU default (x64 off) this silently "
                        "truncates to float32; with jax_enable_x64 it "
                        "cripples MXU rate — use float32 (accumulate in "
                        "f32, not f64)", sev)
                    continue
                # 16-bit reduction without f32 accumulation
                if leaf in _REDUCERS and "dtype" not in kw:
                    operand = None
                    if d and "." in d and node.args:
                        prefix = d.rsplit(".", 1)[0]
                        if prefix in ctx.aliases.get("jax.numpy", ()) \
                                or prefix in ctx.aliases.get("numpy", ()):
                            operand = node.args[0]
                        elif isinstance(node.func, ast.Attribute):
                            operand = node.func.value  # x.sum() method
                    elif isinstance(node.func, ast.Attribute):
                        operand = node.func.value
                    if operand is not None \
                            and interp.eval(operand, env).dtype in _F16:
                        yield self.finding(
                            ctx, node.lineno,
                            f"{leaf}() over a bfloat16/float16 value "
                            f"accumulates in the 16-bit dtype — mass "
                            f"is lost at scale; pass dtype=jnp.float32 "
                            f"(or upcast the operand) and round once "
                            f"at the end", sev)
                        continue
                # 16-bit MXU dot without preferred_element_type
                if leaf in _DOTS and "preferred_element_type" not in kw:
                    ops = node.args[:2]
                    if any(interp.eval(o, env).dtype in _F16
                           for o in ops):
                        yield self.finding(
                            ctx, node.lineno,
                            f"{leaf}() on bfloat16/float16 operands "
                            f"without preferred_element_type=jnp."
                            f"float32 — the MXU accumulates at full "
                            f"rate in f32 for free; without it the "
                            f"product rounds per-tile in 16 bits", sev)

    # -- (3) 16-bit scan carries -------------------------------------------
    def _scan_carries(self, ctx, interp, sev):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not d or d.split(".")[-1] != "scan" \
                    or "lax" not in d.split("."):
                continue
            if len(node.args) < 2:
                continue
            body = None
            if isinstance(node.args[0], ast.Name):
                body = ctx._resolve_local_fn(node, node.args[0].id)
            if body is None or isinstance(body, ast.Lambda):
                continue
            scope = ctx._enclosing_scope(node)
            caller_env = interp.env_of(scope) \
                if not isinstance(scope, ast.Module) \
                else interp.module_env()
            init = node.args[1]
            init_elts: List[Abs]
            if isinstance(init, (ast.Tuple, ast.List)):
                init_elts = [interp.eval(e, caller_env)
                             for e in init.elts]
            else:
                folded = interp.eval(init, caller_env)
                # a tuple init bound through a name folds to its elements
                init_elts = folded.elts if folded.elts is not None \
                    else [folded]
            params = [p.arg for p in body.args.args]
            if not params:
                continue
            carry_name = params[0]
            # map carry slots: `a, b = carry` unpack, or the carry used
            # whole (single-component init)
            slots: Dict[str, int] = {}
            for stmt in body.body:
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.value, ast.Name) \
                        and stmt.value.id == carry_name \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0],
                                       (ast.Tuple, ast.List)):
                    for i, t in enumerate(stmt.targets[0].elts):
                        if isinstance(t, ast.Name):
                            slots[t.id] = i
            if len(init_elts) == 1:
                slots.setdefault(carry_name, 0)
            # body signature `def f(carry, x)` where carry IS a tuple
            # param destructured via subscripts — skip (unresolvable)
            for stmt in ast.walk(body):
                target = None
                if isinstance(stmt, ast.AugAssign) \
                        and isinstance(stmt.op, ast.Add) \
                        and isinstance(stmt.target, ast.Name):
                    target = stmt.target.id
                elif isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.BinOp) \
                        and isinstance(stmt.value.op, ast.Add):
                    tname = stmt.targets[0].id
                    sides = (stmt.value.left, stmt.value.right)
                    if any(isinstance(s, ast.Name) and s.id == tname
                           for s in sides):
                        target = tname
                if target is None or target not in slots:
                    continue
                slot = slots[target]
                if slot >= len(init_elts):
                    continue
                if init_elts[slot].dtype in _F16:
                    yield self.finding(
                        ctx, stmt.lineno,
                        f"scan carry '{target}' is initialized in "
                        f"{init_elts[slot].dtype} and accumulated into "
                        f"— every fold rounds to 16 bits; keep the "
                        f"carry f32 (init with jnp.float32 / .astype("
                        f"jnp.float32)) and round once after the scan",
                        sev)


# ---------------------------------------------------------------------------
# ZL022 — mesh-axis discipline
# ---------------------------------------------------------------------------

def _module_str_consts(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _fold_axis_names(node: ast.AST, consts: Dict[str, str],
                     tree: ast.Module) -> List[str]:
    """Axis-name strings out of a Mesh axis-names argument: a tuple/list
    of string literals and/or names resolving through module string
    constants; a bare Name resolving to a module-level tuple constant."""
    out: List[str] = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                d = dotted(e)
                if d and d.split(".")[-1] in consts:
                    out.append(consts[d.split(".")[-1]])
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    elif isinstance(node, ast.Name):
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == node.id:
                out.extend(_fold_axis_names(stmt.value, consts, tree))
    return out


def extract_axis_decls(ctx: ModuleContext
                       ) -> Tuple[Dict[str, int], Dict[str, str]]:
    """``(vocabulary, axis_constants)`` for one module: axis names
    declared by a jax ``Mesh(devices, (names...))`` / ``make_mesh``
    construction (line = the construction), and the module string
    constants that spell them (``DATA_AXIS = "data"``) so references via
    ``mesh_lib.DATA_AXIS`` resolve."""
    consts = _module_str_consts(ctx.tree)
    vocab: Dict[str, int] = {}
    mods, froms = ctx.jax_names
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if not d:
            continue
        leaf = d.split(".")[-1]
        is_mesh = False
        if leaf in ("Mesh", "make_mesh"):
            if "." in d:
                prefix = d.rsplit(".", 1)[0]
                # `jax.sharding.Mesh` with only `import jax`: the prefix
                # root resolves, not the full dotted prefix
                is_mesh = prefix in mods or prefix.split(".", 1)[0] in mods
            else:
                is_mesh = froms.get(d) in ("Mesh", "make_mesh")
        if not is_mesh:
            continue
        axis_arg: Optional[ast.AST] = None
        if len(node.args) >= 2:
            axis_arg = node.args[1]
        for k in node.keywords:
            if k.arg in ("axis_names", "axis_name"):
                axis_arg = k.value
        if axis_arg is None:
            continue
        for name in _fold_axis_names(axis_arg, consts, ctx.tree):
            vocab.setdefault(name, node.lineno)
    axis_consts = {n: v for n, v in consts.items() if v in vocab}
    return vocab, axis_consts


#: package-relative locations an axis vocabulary module may live at
_MESH_MODULE_CANDIDATES = (os.path.join("parallel", "mesh.py"), "mesh.py")
_VOCAB_CACHE: Dict[str, Tuple[Dict[str, int], Dict[str, str], str]] = {}


def _package_root(path: str) -> Optional[str]:
    """Topmost directory on ``path``'s parent chain that still carries an
    ``__init__.py`` — the scanned file's package root."""
    d = os.path.dirname(os.path.abspath(path))
    root = None
    while os.path.isfile(os.path.join(d, "__init__.py")):
        root = d
        nd = os.path.dirname(d)
        if nd == d:
            break
        d = nd
    return root


def package_axis_vocabulary(path: str
                            ) -> Tuple[Dict[str, int], Dict[str, str], str]:
    """The axis vocabulary of the package ``path`` belongs to: parsed
    from ``<pkg>/parallel/mesh.py`` (or ``<pkg>/mesh.py``), cached per
    package root. Returns ``(vocab, axis_constants, mesh_path)``."""
    root = _package_root(path)
    if root is None:
        return {}, {}, ""
    cached = _VOCAB_CACHE.get(root)
    if cached is not None:
        return cached
    vocab: Dict[str, int] = {}
    consts: Dict[str, str] = {}
    mesh_path = ""
    for cand in _MESH_MODULE_CANDIDATES:
        p = os.path.join(root, cand)
        if not os.path.isfile(p):
            continue
        try:
            with open(p, encoding="utf-8") as f:
                mctx = ModuleContext(p, f.read())
        # an unparseable mesh module: the per-file scan reports ZL000
        except Exception:  # zoolint: disable=ZL007
            continue
        v, c = extract_axis_decls(mctx)
        if v:
            vocab.update(v)
            consts.update(c)
            mesh_path = p
            break
    _VOCAB_CACHE[root] = (vocab, consts, mesh_path)
    return vocab, consts, mesh_path


_COLLECTIVES = {"psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
                "all_gather": 1, "ppermute": 1, "all_to_all": 1,
                "psum_scatter": 1, "pbroadcast": 1, "pshuffle": 1,
                "axis_index": 0, "axis_size": 0}


@dataclasses.dataclass
class AxisUse:
    axis: str
    line: int
    where: str          # "PartitionSpec" | the collective name


def iter_axis_uses(ctx: ModuleContext,
                   consts: Dict[str, str]) -> Iterator[AxisUse]:
    """Every resolvable mesh-axis reference in one module: string
    literals (and ``consts``-resolved names) inside ``PartitionSpec``
    calls and collective ``axis_name`` arguments. Unresolvable names
    (parameters, foreign variables) are skipped — precision over
    recall on an error-severity rule."""
    mods, froms = ctx.jax_names

    def resolve(e: ast.AST) -> Optional[str]:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            return e.value
        d = dotted(e)
        if d and d.split(".")[-1] in consts:
            return consts[d.split(".")[-1]]
        return None

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if not d:
            continue
        leaf = d.split(".")[-1]
        is_pspec = False
        if leaf == "PartitionSpec":
            prefix = d.rsplit(".", 1)[0] if "." in d else ""
            is_pspec = not prefix or prefix in mods \
                or prefix.split(".", 1)[0] in mods
        elif "." not in d and froms.get(d) == "PartitionSpec":
            is_pspec, leaf = True, "PartitionSpec"
        if is_pspec:
            for arg in node.args:
                elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) \
                    else [arg]
                for e in elts:
                    axis = resolve(e)
                    if axis is not None:
                        yield AxisUse(axis, node.lineno, "PartitionSpec")
            continue
        if leaf in _COLLECTIVES and "lax" in d.split("."):
            axis_arg: Optional[ast.AST] = None
            pos = _COLLECTIVES[leaf]
            if len(node.args) > pos:
                axis_arg = node.args[pos]
            for k in node.keywords:
                if k.arg == "axis_name":
                    axis_arg = k.value
            if axis_arg is None:
                continue
            elts = axis_arg.elts \
                if isinstance(axis_arg, (ast.Tuple, ast.List)) \
                else [axis_arg]
            for e in elts:
                axis = resolve(e)
                if axis is not None:
                    yield AxisUse(axis, node.lineno, leaf)


@register
class MeshAxisDiscipline(Rule):
    """Mesh-axis discipline (use direction). Every axis name a
    ``PartitionSpec`` or collective (``psum``/``all_gather``/
    ``ppermute``/...) references must come from the declared axis
    vocabulary — the ``Mesh(...)`` axis names extracted from the
    package's ``parallel/mesh.py`` (plus any in-file mesh
    construction). A misspelled or stale axis (``P('data', 'modell')``)
    passes every single-chip CPU test and only explodes at trace time
    on a multi-chip mesh CI doesn't have. Inert when no mesh
    construction is visible. The project pass (``--contracts``) adds
    the reverse direction: declared axes nothing references, at
    warning severity."""

    id = "ZL022"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        vocab, consts = extract_axis_decls(ctx)
        pvocab, pconsts, mesh_path = package_axis_vocabulary(ctx.path)
        # the file's own mesh module declares for itself
        if os.path.abspath(ctx.path) == os.path.abspath(mesh_path or ""):
            pvocab, pconsts = {}, {}
        vocab = {**pvocab, **vocab}
        consts = {**pconsts, **consts}
        if not vocab:
            return
        sev = ERROR if _in_package(ctx.path) else WARNING
        known = sorted(vocab)
        for use in iter_axis_uses(ctx, consts):
            if use.axis not in vocab:
                yield self.finding(
                    ctx, use.line,
                    f"axis '{use.axis}' in {use.where} is not in the "
                    f"declared mesh axis vocabulary {known} "
                    f"{'(' + os.path.basename(mesh_path) + ')' if mesh_path else ''}"
                    f" — a misspelled/stale axis only fails at trace "
                    f"time on a multi-chip mesh", sev)


@register_project
class MeshAxisVocabularyDrift(ProjectRule):
    """Mesh-axis discipline (declaration direction, project pass): a
    declared mesh axis that no ``PartitionSpec``/collective anywhere in
    the package references is a dead topology axis — either the
    consumer drifted away (the sharding silently became a no-op) or
    the axis should be pruned. Warning severity: a deliberately
    reserved axis is legitimate, but it should be visible."""

    id = "ZL022"
    severity = ERROR        # the rule's headline severity (use direction)

    def check(self, project: ProjectContext) -> Iterator[Finding]:
        vocab: Dict[str, Tuple[str, int]] = {}
        consts: Dict[str, str] = {}
        for ctx in project.modules:
            v, c = extract_axis_decls(ctx)
            for name, line in v.items():
                vocab.setdefault(name, (ctx.path, line))
            consts.update(c)
        if not vocab:
            return
        used: Set[str] = set()
        for ctx in project.modules:
            for use in iter_axis_uses(ctx, consts):
                used.add(use.axis)
        for axis, (path, line) in sorted(vocab.items()):
            if axis not in used:
                yield Finding(
                    self.id, WARNING, path, line,
                    f"mesh axis '{axis}' is declared here but no "
                    f"PartitionSpec or collective anywhere references "
                    f"it — dead topology axis (prune it, or the "
                    f"consumer drifted)")


# ---------------------------------------------------------------------------
# ZL023 — Pallas tile alignment
# ---------------------------------------------------------------------------

@register
class PallasTileAlignment(Rule):
    """Pallas block-shape tile alignment. The last two dims of every
    ``BlockSpec`` block shape and ``pltpu.VMEM`` scratch shape must be
    *provably* on the hardware tile floors (trailing dim a multiple of
    LANES=128, second-to-last of SUBLANES=8): aligned constants,
    ``round_up(x, floor)`` wraps, ``x // m * m`` floors, and dims taken
    whole off an array's ``.shape`` (Mosaic pads whole-axis blocks) all
    prove out. Flagged: constants off the floor, and clamp derivations
    (``min(block, t)``, bare ``// 2`` halving) that can leave the floor
    — the exact bug class that compiles on the interpreter and dies in
    Mosaic only on a real TPU. Error in package code, warning
    elsewhere."""

    id = "ZL023"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not uses_pallas(ctx):
            return
        lanes, sublanes = _tile_floors()
        sev = ERROR if _in_package(ctx.path) else WARNING
        interp = Interp(ctx)
        env_cache: Dict[int, Dict[str, Abs]] = {}

        def env_for(node) -> Dict[str, Abs]:
            scope = ctx._enclosing_scope(node)
            while isinstance(scope, ast.ClassDef):
                scope = ctx._enclosing_scope(scope)
            key = id(scope)
            if key not in env_cache:
                env_cache[key] = interp.module_env() \
                    if isinstance(scope, ast.Module) \
                    else interp.env_of(scope)
            return env_cache[key]

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            shape_node: Optional[ast.AST] = None
            what = ""
            if _is_pallas_attr(ctx, node.func, ("BlockSpec",)):
                if node.args:
                    shape_node = node.args[0]
                for k in node.keywords:
                    if k.arg == "block_shape":
                        shape_node = k.value
                what = "BlockSpec block shape"
            elif _is_pallas_attr(ctx, node.func, ("VMEM",)) and node.args:
                shape_node = node.args[0]
                what = "VMEM scratch shape"
            if not isinstance(shape_node, (ast.Tuple, ast.List)) \
                    or len(shape_node.elts) < 1:
                continue
            env = env_for(node)
            dims = shape_node.elts
            checks = [(dims[-1], lanes, "last")]
            if len(dims) >= 2:
                checks.append((dims[-2], sublanes, "second-to-last"))
            for dim, floor, pos in checks:
                a = interp.eval(dim, env)
                if a.from_shape:
                    continue        # whole-axis dim: Mosaic pads it
                if a.const is not None:
                    if a.const > floor and a.const % floor != 0:
                        yield self.finding(
                            ctx, node.lineno,
                            f"{what}: {pos} dim {a.const} is not a "
                            f"multiple of the tile floor ({floor}) — "
                            f"Mosaic rejects it on compiled TPU runs "
                            f"(the interpreter does not care); "
                            f"round_up() it", sev)
                    continue
                if a.clamped and a.align % floor != 0:
                    yield self.finding(
                        ctx, node.lineno,
                        f"{what}: {pos} dim is derived through a raw "
                        f"clamp (min()/floor-div) that can leave the "
                        f"{floor}-tile floor — wrap it in round_up(..., "
                        f"{'LANES' if floor == lanes else 'SUBLANES'}) "
                        f"like select_attention_blocks does", sev)


# ---------------------------------------------------------------------------
# ZL024 — static VMEM budget
# ---------------------------------------------------------------------------

def _local_list(ctx: ModuleContext, at: ast.AST,
                name: str) -> Optional[ast.AST]:
    """The single local ``name = [...]`` list-literal binding visible
    from ``at`` (conditional ``.append`` calls are invisible — fine for
    a LOWER-bound footprint)."""
    scope = ctx._enclosing_scope(at)
    while scope is not None:
        found: List[ast.AST] = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name \
                            and isinstance(node.value, (ast.List,
                                                        ast.Tuple)):
                        found.append(node.value)
        if found:
            return found[0] if len(found) == 1 else None
        if isinstance(scope, ast.Module):
            return None
        scope = ctx._enclosing_scope(scope)
    return None


@register
class PallasStaticVmemBudget(Rule):
    """Static VMEM budget for ``pallas_call`` sites. A provable LOWER
    bound on the kernel's footprint — double-buffered operand/output
    windows + scratch, every unknown dim priced at the tile floor and
    unknown dtypes at 1 byte — is computed with the SAME parameterized
    estimator the runtime block autotuner uses
    (``ops/pallas/common.kernel_vmem_bytes``; the flash-attention
    selector, the fused-CE clamp and this rule share one formula) and
    held against the 16 MiB per-core default budget. A site whose
    guaranteed-minimum footprint already exceeds the budget cannot
    compile on a default TPU core at ANY signature — it fails lint
    instead of a TPU run. Error in package code, warning elsewhere."""

    id = "ZL024"
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not uses_pallas(ctx):
            return
        mod = footprint_module()
        if mod is None:
            return              # no estimator available: skip, not guess
        sev = ERROR if _in_package(ctx.path) else WARNING
        interp = Interp(ctx)

        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and _is_pallas_attr(ctx, node.func, ("pallas_call",))):
                continue
            scope = ctx._enclosing_scope(node)
            env = interp.module_env() if isinstance(scope, ast.Module) \
                else interp.env_of(scope)
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            operands = self._spec_windows(ctx, interp, env, node,
                                          kw.get("in_specs"))
            outputs = self._spec_windows(ctx, interp, env, node,
                                         kw.get("out_specs"))
            scratch = self._scratch_windows(ctx, interp, env, node,
                                            kw.get("scratch_shapes"))
            if not (operands or outputs or scratch):
                continue
            footprint = mod.kernel_vmem_bytes(
                operands=operands, outputs=outputs, scratch=scratch)
            budget = int(mod.VMEM_BYTES_DEFAULT)
            if footprint > budget:
                yield self.finding(
                    ctx, node.lineno,
                    f"pallas_call's windows are provably at least "
                    f"{footprint / 2 ** 20:.1f} MiB of VMEM "
                    f"(double-buffered operands + outputs + scratch, "
                    f"unknown dims priced at the tile floor) — over "
                    f"the {budget // 2 ** 20} MiB per-core budget the "
                    f"runtime autotuner fits kernels into; shrink the "
                    f"block shapes or stream the operand", sev)

    def _items(self, ctx, at, spec_node) -> List[ast.AST]:
        if spec_node is None:
            return []
        if isinstance(spec_node, ast.Name):
            spec_node = _local_list(ctx, at, spec_node.id)
        if isinstance(spec_node, (ast.List, ast.Tuple)):
            return list(spec_node.elts)
        if isinstance(spec_node, ast.Call):
            return [spec_node]
        return []

    def _lower_dims(self, interp, env, shape_node) -> Optional[List[int]]:
        if not isinstance(shape_node, (ast.Tuple, ast.List)):
            return None
        return [max(interp.eval(e, env).low, 1)
                for e in shape_node.elts]

    def _spec_windows(self, ctx, interp, env, at, spec_node):
        out = []
        for item in self._items(ctx, at, spec_node):
            if not (isinstance(item, ast.Call)
                    and _is_pallas_attr(ctx, item.func, ("BlockSpec",))):
                continue
            shape_node = item.args[0] if item.args else None
            for k in item.keywords:
                if k.arg == "block_shape":
                    shape_node = k.value
            dims = self._lower_dims(interp, env, shape_node)
            if dims:
                out.append((tuple(dims), 1))    # unknown dtype: 1 B floor
        return out

    def _scratch_windows(self, ctx, interp, env, at, spec_node):
        out = []
        for item in self._items(ctx, at, spec_node):
            if not (isinstance(item, ast.Call)
                    and _is_pallas_attr(ctx, item.func, ("VMEM",))):
                continue
            dims = self._lower_dims(interp, env,
                                    item.args[0] if item.args else None)
            if not dims:
                continue
            itemsize = 1
            if len(item.args) >= 2:
                dt = dtype_of_node(ctx, item.args[1])
                if dt:
                    itemsize = _ITEMSIZE.get(dt, 1)
            out.append((tuple(dims), itemsize))
        return out
