import os
import sys

from .cli import main

try:
    code = main()
except BrokenPipeError:     # e.g. `... | head` closed the pipe mid-print
    os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    # findings may have been truncated before the gate could count them —
    # the conventional SIGPIPE status keeps a piped lint run from
    # reading as "0 errors"
    code = 128 + 13
sys.exit(code)
