"""Device-mesh management — the TPU-native replacement for the reference's
Spark executor topology.

In the reference (robert-sbd/analytics-zoo), physical parallelism is organised by
``Engine.init`` counting Spark executors and cores
(``common/NNContext.scala:133-149``) and data parallelism is the only axis
(``docs/docs/wp-bigdl.md:113``).  Here the physical layer is a
``jax.sharding.Mesh`` over TPU chips with up to four logical axes:

* ``data``  — data parallelism (the reference's per-partition model replicas,
  ``Topology.scala:1150-1158``),
* ``model`` — tensor/model parallelism (absent in the reference; greenfield),
* ``seq``   — sequence/context parallelism (absent in the reference),
* ``expert`` — expert parallelism for MoE layers (absent in the reference),
* ``pipe``  — pipeline parallelism (GPipe microbatch schedule; absent in the
  reference).

Collectives ride ICI within a mesh; XLA inserts psum/all-gather from sharding
annotations, replacing BigDL's Spark-BlockManager ``AllReduceParameter``
(``wp-bigdl.md:140-160``).
"""

from __future__ import annotations

import math
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"

ALL_AXES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS, PIPE_AXIS)

_global_mesh: Optional[Mesh] = None


def create_mesh(
    data: int = -1,
    model: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a logical mesh over the available devices.

    ``data=-1`` means "absorb all remaining devices", mirroring how the
    reference sizes data parallelism to the cluster (one model replica per
    Spark partition, ``Topology.scala:1102-1110``).

    The axis order is (data, pipe, seq, expert, model), placing the model
    axis innermost so tensor-parallel collectives ride the fastest ICI links
    and the pipe axis outermost-but-one so stage hops cross the slowest
    links only once per microbatch.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = model * seq * expert * pipe
    if data == -1:
        if n % fixed != 0:
            raise ValueError(
                f"device count {n} not divisible by "
                f"model*seq*expert*pipe={fixed}"
            )
        data = n // fixed
    total = data * fixed
    if total != n:
        raise ValueError(
            f"mesh {data}x{pipe}x{seq}x{expert}x{model}={total} "
            f"!= device count {n}"
        )
    dev_array = np.asarray(devices).reshape(data, pipe, seq, expert, model)
    return Mesh(dev_array,
                (DATA_AXIS, PIPE_AXIS, SEQ_AXIS, EXPERT_AXIS, MODEL_AXIS))


def set_global_mesh(mesh: Mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def global_mesh() -> Mesh:
    """Return the process-wide mesh, creating a pure-DP mesh on first use."""
    global _global_mesh
    if _global_mesh is None:
        _global_mesh = create_mesh()
    return _global_mesh


def reset_global_mesh() -> None:
    global _global_mesh
    _global_mesh = None


def data_parallel_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or global_mesh()
    return mesh.shape[DATA_AXIS]


def mesh_metadata(mesh: Optional[Mesh] = None) -> dict:
    """JSON-serializable topology descriptor — stored in checkpoint
    manifests (``utils/checkpoint.py``) so a restore under a DIFFERENT
    device count/mesh shape is detected and re-placed instead of
    silently mis-sharded. Host-side snapshot leaves are topology-free;
    this records only what the snapshot was cut under."""
    mesh = mesh or global_mesh()
    return {"axes": {str(k): int(v) for k, v in mesh.shape.items()},
            "devices": int(mesh.devices.size)}


def format_mesh(meta: Optional[dict]) -> str:
    """Compact human form of :func:`mesh_metadata` output for log lines:
    ``{data:8}`` (singleton axes elided; ``{}`` when all are 1)."""
    axes = (meta or {}).get("axes", {}) or {}
    kept = {k: v for k, v in axes.items() if int(v) != 1}
    inner = ", ".join(f"{k}:{v}" for k, v in kept.items())
    return "{" + inner + "}"


def batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis."""
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully-replicated sharding (the reference replicates parameters whole
    per worker, ``Topology.scala:1118-1120``)."""
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P())


def stacked_batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for a stacked chunk of K minibatches ``(K, batch, ...)``:
    the scan axis stays replicated, the batch axis splits over data."""
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P(None, DATA_AXIS))


def param_shardings(model, params, mesh: Optional[Mesh] = None):
    """Per-leaf NamedSharding tree for a model's params: layers declare
    PartitionSpecs over the ``model`` axis via ``Layer.param_sharding``
    (Dense/Embedding shard; everything else replicates). On a mesh without
    tensor parallelism everything replicates — the pure-DP fast path."""
    import jax

    mesh = mesh or global_mesh()
    repl = replicated_sharding(mesh)
    # fast path only when NO param-bearing axis exists: expert-stacked MoE
    # weights shard over ``expert``, GPipe stage stacks over ``pipe``, even
    # without tensor parallelism
    if (mesh.shape[MODEL_AXIS] * mesh.shape[EXPERT_AXIS]
            * mesh.shape[PIPE_AXIS] == 1
            or not hasattr(model, "param_sharding")):
        return jax.tree.map(lambda _: repl, params)
    spec_tree = model.param_sharding(params)
    fallbacks: list = []

    def to_sharding(path, spec, leaf):
        if spec is None:
            return repl
        # a dim that doesn't divide by its axis size can't shard — fall back
        # to replicated for that leaf (e.g. a 3-class head under model=2)
        shape = np.shape(leaf)
        for i, ax in enumerate(spec):
            if ax is None:
                continue
            if i >= len(shape) or shape[i] % mesh.shape[ax] != 0:
                fallbacks.append((jax.tree_util.keystr(path), shape, spec))
                return repl
        return NamedSharding(mesh, spec)

    out = jax.tree_util.tree_map_with_path(
        to_sharding, spec_tree, params,
        is_leaf=lambda s: s is None or isinstance(s, P))
    if fallbacks:
        # ONE summary line — count + first offender. Per-leaf spam (a W
        # and b line per undividable head, re-listed on every run) buried
        # the signal in multichip logs; anyone chasing the rest can log
        # analytics_zoo_tpu.mesh at DEBUG.
        import logging
        logger = logging.getLogger("analytics_zoo_tpu.mesh")
        first_p, first_s, first_sp = fallbacks[0]
        logger.warning(
            "%d param leaf/leaves replicated instead of model-sharded "
            "(dim not divisible by axis size); first offender: %s shape=%s "
            "spec=%s", len(fallbacks), first_p, first_s, first_sp)
        if len(fallbacks) > 1:
            logger.debug("all replicated-fallback leaves: %s",
                         "; ".join(f"{p} shape={s} spec={sp}"
                                   for p, s, sp in fallbacks))
    return out


def zero_sharding_for(base: NamedSharding, shape,
                      mesh: Optional[Mesh] = None) -> NamedSharding:
    """ZeRO-1 placement for one param-shaped optimizer-state leaf (SURVEY
    §2.4: the TPU-native replacement for the reference's sliced
    ``AllReduceParameter``, ``wp-bigdl.md:140-160``, which shards optimizer
    state across workers): take the leaf's existing param sharding (model/
    expert axes intact) and partition the first still-unsharded dim whose
    size divides the ``data`` axis. Leaves with no such dim stay on their
    base sharding — correct, just not memory-sharded.

    Under jit this annotation is all GSPMD needs: the gradient reduction
    feeding the moment update lowers to reduce-scatter and the updated
    params all-gather back, instead of a full all-reduce with replicated
    moments."""
    mesh = mesh or global_mesh()
    dp = mesh.shape[DATA_AXIS]
    if dp <= 1:
        return base
    spec = list(base.spec) + [None] * (len(shape) - len(base.spec))
    for i, (ax, dim) in enumerate(zip(spec, shape)):
        if ax is None and dim % dp == 0:
            spec[i] = DATA_AXIS
            return NamedSharding(mesh, P(*spec))
    return base
