"""Device-mesh management — the TPU-native replacement for the reference's
Spark executor topology.

In the reference (robert-sbd/analytics-zoo), physical parallelism is organised by
``Engine.init`` counting Spark executors and cores
(``common/NNContext.scala:133-149``) and data parallelism is the only axis
(``docs/docs/wp-bigdl.md:113``).  Here the physical layer is a
``jax.sharding.Mesh`` over TPU chips with up to four logical axes:

* ``data``  — data parallelism (the reference's per-partition model replicas,
  ``Topology.scala:1150-1158``),
* ``model`` — tensor/model parallelism (absent in the reference; greenfield),
* ``seq``   — sequence/context parallelism (absent in the reference),
* ``expert`` — expert parallelism for MoE layers (absent in the reference).

Collectives ride ICI within a mesh; XLA inserts psum/all-gather from sharding
annotations, replacing BigDL's Spark-BlockManager ``AllReduceParameter``
(``wp-bigdl.md:140-160``).
"""

from __future__ import annotations

import math
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"

ALL_AXES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS)

_global_mesh: Optional[Mesh] = None


def create_mesh(
    data: int = -1,
    model: int = 1,
    seq: int = 1,
    expert: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a logical mesh over the available devices.

    ``data=-1`` means "absorb all remaining devices", mirroring how the
    reference sizes data parallelism to the cluster (one model replica per
    Spark partition, ``Topology.scala:1102-1110``).

    The axis order is (data, seq, expert, model), placing the model axis
    innermost so tensor-parallel collectives ride the fastest ICI links.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = model * seq * expert
    if data == -1:
        if n % fixed != 0:
            raise ValueError(
                f"device count {n} not divisible by model*seq*expert={fixed}"
            )
        data = n // fixed
    total = data * fixed
    if total != n:
        raise ValueError(
            f"mesh {data}x{seq}x{expert}x{model}={total} != device count {n}"
        )
    dev_array = np.asarray(devices).reshape(data, seq, expert, model)
    return Mesh(dev_array, (DATA_AXIS, SEQ_AXIS, EXPERT_AXIS, MODEL_AXIS))


def set_global_mesh(mesh: Mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def global_mesh() -> Mesh:
    """Return the process-wide mesh, creating a pure-DP mesh on first use."""
    global _global_mesh
    if _global_mesh is None:
        _global_mesh = create_mesh()
    return _global_mesh


def reset_global_mesh() -> None:
    global _global_mesh
    _global_mesh = None


def data_parallel_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or global_mesh()
    return mesh.shape[DATA_AXIS]


def batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis."""
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully-replicated sharding (the reference replicates parameters whole
    per worker, ``Topology.scala:1118-1120``)."""
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P())


def stacked_batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for a stacked chunk of K minibatches ``(K, batch, ...)``:
    the scan axis stays replicated, the batch axis splits over data."""
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P(None, DATA_AXIS))
