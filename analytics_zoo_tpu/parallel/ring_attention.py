"""Ring + Ulysses attention — sequence/context parallelism over the ``seq``
mesh axis.

The reference has NO long-context mechanism (SURVEY §5: sequences are padded
to one core's memory, attention is plain full self-attention inside
``TransformerLayer.scala``/``BERT.scala:66``), so this is greenfield TPU
design. Two routings, both under ``shard_map``:

* **Ring** (``ring_self_attention``): the sequence dim stays sharded, each
  device holds its Q/K/V block, and K/V blocks rotate around the ring via
  ``ppermute`` while a numerically-stable online softmax accumulates output
  blocks — attention memory per device is O(T/seq_shards * T_block) and the
  ppermute rides ICI (the blockwise/ring attention construction of Liu et
  al., re-derived for ``shard_map``). Key-padding masks stream WITH the ring:
  each rank's (B, T_local) mask slice rotates alongside its K/V block, so
  BERT-shaped masked models ride the seq mesh too (VERDICT r4 missing #1).
* **Ulysses** (``ulysses_self_attention``): an all-to-all re-shards heads
  over the seq axis (H/n heads, FULL sequence per device), attention runs as
  one dense local op on the MXU, and a second all-to-all restores the
  sequence sharding. Two collectives total instead of the ring's n-1
  ppermutes — the better trade when n_head divides over the axis and the
  full-T score block fits HBM.

Math (flash-style streaming softmax, all in float32): for each incoming K/V
block, s = q·k/sqrt(d); m' = max(m, max_allowed(s)); o = o*exp(m-m') +
exp(s-m')·v (masked entries contribute 0); l likewise; final out = o/l.
Fully-masked blocks leave (o, m, l) untouched by construction.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import compat
from . import mesh as mesh_lib

__all__ = ["ring_attention", "ring_self_attention", "ulysses_self_attention"]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False,
                   kv_mask: Optional[jax.Array] = None,
                   dropout_rate: float = 0.0,
                   dropout_rng: Optional[jax.Array] = None) -> jax.Array:
    """Blockwise ring attention INSIDE a ``shard_map`` over ``axis_name``.

    q, k, v: local blocks (B, H, T_local, D) — the sequence dim is sharded
    over ``axis_name``. ``kv_mask``: this rank's (B, T_local) key-padding
    slice (True/1 = attend); it rotates with the K/V blocks. Returns the
    local output block (B, H, T_local, D). ``causal`` masks with GLOBAL
    positions (block i attends to block j<=i, and within the diagonal block
    the usual triangular mask).

    ``dropout_rate``/``dropout_rng``: attention-probability dropout. Each
    (q-block, k-block) pair draws its mask from a key folded with BOTH
    global block indices, so the pattern is a pure function of global
    position — self-consistent however the ring rotates (it will not
    bitwise-match the single-chip XLA op's stream; like GPipe's
    per-microbatch keys, dropout decorrelates across placements, not
    across steps). The softmax normalizer ``l`` accumulates the
    PRE-dropout probabilities while ``o`` accumulates the
    inverted-dropout ones, so ``o/l`` is EXACTLY the reference
    semantics — dropout applied to the normalized weights, no
    self-normalization bias.
    """
    # axis_name is caller-supplied, so the collectives below must stay
    # within the axes documented for psum/axis_index/ppermute in
    # PARALLELISM.md's collective catalog (reconciled by ZL025).
    n_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32)

    q_pos = my_idx * t_local + jnp.arange(t_local)          # global q rows
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    mask_blk0 = (None if kv_mask is None
                 else kv_mask.astype(jnp.bool_))

    def accumulate(o, m, l, k_blk, v_blk, mask_blk, i):
        src = (my_idx - i) % n_shards                       # block owner
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32))
        s = s * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            allowed = q_pos[:, None] >= k_pos[None, :]      # (Tq, Tk)
            allowed = allowed[None, None]
        else:
            allowed = jnp.ones((1, 1, t_local, t_local), jnp.bool_)
        if mask_blk is not None:
            allowed = allowed & mask_blk[:, None, None, :]  # (B, 1, 1, Tk)
        s_masked = jnp.where(allowed, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s_masked, axis=-1, keepdims=True))
        # exp(-inf - finite) = 0 handles both masked entries and the
        # not-yet-seen-anything m = -inf state; guard the all-masked case
        # where m_new is still -inf (exp(nan) otherwise)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(allowed, jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0 and dropout_rng is not None:
            blk_key = jax.random.fold_in(
                jax.random.fold_in(dropout_rng, my_idx), src)
            keep = jax.random.bernoulli(blk_key, 1.0 - dropout_rate,
                                        p.shape)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        o = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p,
                                  v_blk.astype(jnp.float32))
        return o, m_new, l

    def step(carry, i):
        o, m, l, k_blk, v_blk, mask_blk = carry
        o, m, l = accumulate(o, m, l, k_blk, v_blk, mask_blk, i)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if mask_blk is not None:
            mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk, mask_blk), None

    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    # scan rotates K/V after each accumulation; the LAST block is folded in
    # outside the scan so the ring doesn't pay one final discarded ppermute
    (o, m, l, k_last, v_last, mask_last), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v, mask_blk0), jnp.arange(n_shards - 1))
    o, _, l = accumulate(o, m, l, k_last, v_last, mask_last, n_shards - 1)
    out = o / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


def _seq_specs(mask):
    spec = P(mesh_lib.DATA_AXIS, None, mesh_lib.SEQ_AXIS, None)
    mask_spec = P(mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS)
    in_specs = (spec, spec, spec) + ((mask_spec,) if mask is not None else ())
    return spec, in_specs


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mesh: Optional[Mesh] = None,
                        causal: bool = False,
                        mask: Optional[jax.Array] = None,
                        dropout_rate: float = 0.0,
                        dropout_rng: Optional[jax.Array] = None) -> jax.Array:
    """Entry point on GLOBAL arrays: q/k/v (B, H, T, D) with T sharded over
    the ``seq`` axis (and batch over ``data``); runs the ring under
    ``shard_map``. ``mask``: global (B, T) key-padding mask (1 = attend),
    sharded the same way — each rank streams its slice around the ring.
    ``dropout_rate``/``dropout_rng``: attention dropout, block-position-
    keyed (see ``ring_attention``). T must divide evenly by the seq-axis
    size."""
    mesh = mesh or mesh_lib.global_mesh()
    n_seq = mesh.shape[mesh_lib.SEQ_AXIS]
    t = q.shape[2]
    if t % max(n_seq, 1) != 0:
        raise ValueError(f"sequence length {t} not divisible by seq axis "
                         f"size {n_seq}")
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 needs dropout_rng")
    spec, in_specs = _seq_specs(mask)
    if dropout_rng is not None:
        in_specs = in_specs + (P(),)          # the key is replicated

    def local(*args):
        args = list(args)
        qb, kb, vb = args[:3]
        rng_loc = args.pop() if dropout_rng is not None else None
        if rng_loc is not None:
            # distinct masks for the batch rows on each data shard
            rng_loc = jax.random.fold_in(
                rng_loc, jax.lax.axis_index(mesh_lib.DATA_AXIS))
        mb = args[3] if len(args) > 3 else None
        return ring_attention(qb, kb, vb, axis_name=mesh_lib.SEQ_AXIS,
                              causal=causal, kv_mask=mb,
                              dropout_rate=dropout_rate,
                              dropout_rng=rng_loc)

    fn = compat.shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=spec,
                       check_vma=False)
    args = (q, k, v) + ((mask,) if mask is not None else ())
    args = args + ((dropout_rng,) if dropout_rng is not None else ())
    return fn(*args)


def ulysses_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Optional[Mesh] = None,
                           causal: bool = False,
                           mask: Optional[jax.Array] = None,
                           dropout_rate: float = 0.0,
                           dropout_rng: Optional[jax.Array] = None
                           ) -> jax.Array:
    """Ulysses-style sequence parallelism (SURVEY §5's head-vs-sequence
    all-to-all): q/k/v (B, H, T, D) arrive sequence-sharded; an all-to-all
    converts to head-sharded/full-sequence, attention runs as ONE dense
    local op (the full (T, T) score block tiles straight onto the MXU), and
    a second all-to-all restores the sequence sharding. n_head must divide
    by the seq-axis size."""
    mesh = mesh or mesh_lib.global_mesh()
    n_seq = mesh.shape[mesh_lib.SEQ_AXIS]
    t, h = q.shape[2], q.shape[1]
    if t % max(n_seq, 1) != 0:
        raise ValueError(f"sequence length {t} not divisible by seq axis "
                         f"size {n_seq}")
    if h % max(n_seq, 1) != 0:
        raise ValueError(f"n_head {h} not divisible by seq axis size "
                         f"{n_seq} — use ring attention instead")
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 needs dropout_rng")
    spec, in_specs = _seq_specs(mask)
    if dropout_rng is not None:
        in_specs = in_specs + (P(),)          # the key is replicated
    axis = mesh_lib.SEQ_AXIS

    def local(*args):
        args = list(args)
        qb, kb, vb = args[:3]
        rng_loc = args.pop() if dropout_rng is not None else None
        if rng_loc is not None:
            # distinct masks per (data shard, head shard)
            rng_loc = jax.random.fold_in(
                jax.random.fold_in(
                    rng_loc, jax.lax.axis_index(mesh_lib.DATA_AXIS)),
                jax.lax.axis_index(axis))
        mb = args[3] if len(args) > 3 else None
        # (B, H, T_local, D) -> (B, H_local, T, D): scatter heads, gather seq
        a2a = functools.partial(jax.lax.all_to_all, axis_name=axis,
                                split_axis=1, concat_axis=2, tiled=True)
        qg, kg, vg = a2a(qb), a2a(kb), a2a(vb)
        full_mask = None
        if mb is not None:
            full_mask = jax.lax.all_gather(
                mb, axis, axis=1, tiled=True)[:, None, None, :]  # (B,1,1,T)
        from ..ops.attention import dot_product_attention
        og = dot_product_attention(qg, kg, vg, mask=full_mask, causal=causal,
                                   dropout_rate=dropout_rate,
                                   dropout_rng=rng_loc)
        # (B, H_local, T, D) -> (B, H, T_local, D): scatter seq, gather heads
        return jax.lax.all_to_all(og, axis_name=axis, split_axis=2,
                                  concat_axis=1, tiled=True)

    fn = compat.shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=spec,
                       check_vma=False)
    args = (q, k, v) + ((mask,) if mask is not None else ())
    args = args + ((dropout_rng,) if dropout_rng is not None else ())
    return fn(*args)
