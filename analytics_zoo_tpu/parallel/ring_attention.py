"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference has NO long-context mechanism (SURVEY §5: sequences are padded
to one core's memory, attention is plain full self-attention inside
``TransformerLayer.scala``/``BERT.scala:66``), so this is greenfield TPU
design: the sequence dim is sharded over the ``seq`` axis, each device holds
its Q/K/V block, and K/V blocks rotate around the ring via ``ppermute`` while
a numerically-stable online softmax accumulates output blocks — attention
memory per device is O(T/seq_shards * T_block) and the ppermute rides ICI
(the blockwise/ring attention construction of Liu et al., re-derived for
``shard_map``).

Math (flash-style streaming softmax, all in float32): for each incoming K/V
block, s = q·k/sqrt(d); m' = max(m, max_allowed(s)); o = o*exp(m-m') +
exp(s-m')·v (masked entries contribute 0); l likewise; final out = o/l.
Fully-masked blocks leave (o, m, l) untouched by construction.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

__all__ = ["ring_attention", "ring_self_attention"]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False) -> jax.Array:
    """Blockwise ring attention INSIDE a ``shard_map`` over ``axis_name``.

    q, k, v: local blocks (B, H, T_local, D) — the sequence dim is sharded
    over ``axis_name``. Returns the local output block (B, H, T_local, D).
    ``causal`` masks with GLOBAL positions (block i attends to block j<=i,
    and within the diagonal block the usual triangular mask).
    """
    n_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    qf = q.astype(jnp.float32)

    q_pos = my_idx * t_local + jnp.arange(t_local)          # global q rows
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def accumulate(o, m, l, k_blk, v_blk, i):
        src = (my_idx - i) % n_shards                       # block owner
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32))
        s = s * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            allowed = q_pos[:, None] >= k_pos[None, :]      # (Tq, Tk)
            allowed = allowed[None, None]
        else:
            allowed = jnp.ones((1, 1, t_local, t_local), jnp.bool_)
        s_masked = jnp.where(allowed, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s_masked, axis=-1, keepdims=True))
        # exp(-inf - finite) = 0 handles both masked entries and the
        # not-yet-seen-anything m = -inf state; guard the all-masked case
        # where m_new is still -inf (exp(nan) otherwise)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(allowed, jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p,
                                  v_blk.astype(jnp.float32))
        return o, m_new, l

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        o, m, l = accumulate(o, m, l, k_blk, v_blk, i)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk), None

    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    # scan rotates K/V after each accumulation; the LAST block is folded in
    # outside the scan so the ring doesn't pay one final discarded ppermute
    (o, m, l, k_last, v_last), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n_shards - 1))
    o, _, l = accumulate(o, m, l, k_last, v_last, n_shards - 1)
    out = o / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mesh: Optional[Mesh] = None,
                        causal: bool = False) -> jax.Array:
    """Entry point on GLOBAL arrays: q/k/v (B, H, T, D) with T sharded over
    the ``seq`` axis (and batch over ``data``); runs the ring under
    ``shard_map``. T must divide evenly by the seq-axis size."""
    mesh = mesh or mesh_lib.global_mesh()
    n_seq = mesh.shape[mesh_lib.SEQ_AXIS]
    t = q.shape[2]
    if t % max(n_seq, 1) != 0:
        raise ValueError(f"sequence length {t} not divisible by seq axis "
                         f"size {n_seq}")
    spec = P(mesh_lib.DATA_AXIS, None, mesh_lib.SEQ_AXIS, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=mesh_lib.SEQ_AXIS,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
