from .mesh import (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS,  # noqa: F401
                   create_mesh, global_mesh, set_global_mesh, reset_global_mesh,
                   batch_sharding, replicated_sharding, data_parallel_size)
