from .mesh import (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS,  # noqa: F401
                   PIPE_AXIS,
                   create_mesh, global_mesh, set_global_mesh, reset_global_mesh,
                   batch_sharding, replicated_sharding, data_parallel_size)
from .pipeline import gpipe_apply, sequential_apply  # noqa: F401
