"""Pipeline parallelism — GPipe microbatch schedule over the ``pipe`` mesh
axis (SURVEY §2.4: PP "NO — no stage partitioner / microbatch scheduler
exists" in the reference; designed fresh for TPU).

The TPU-native shape of pipeline parallelism: stage weights are STACKED into
one ``(S, ...)`` tree sharded over the ``pipe`` axis, and the schedule is a
single ``lax.scan`` of ``n_micro + S - 1`` ticks inside ``shard_map`` — each
tick every pipe rank runs its stage on its current microbatch and the
activations rotate one hop with ``lax.ppermute`` over ICI. No host-side
scheduler, no per-stage processes: XLA sees one fused program, and autodiff
through scan+ppermute yields the backward pipeline for free (1F1B-style
memory tricks are a future refinement; GPipe semantics first).

Two schedulers share the schedule: ``gpipe_apply`` for HOMOGENEOUS stages
(same layer config, shape-preserving — the stacked transformer-block case,
cheapest representation) and ``hetero_gpipe_apply`` for ARBITRARY stage cuts
(per-stage distinct param trees and activation shapes: ``embedding → blocks
→ head`` as one pipelined model, via a packed param buffer + common
activation wire format + ``lax.switch`` per rank). On a mesh without a
``pipe`` axis the same models run sequentially — portable from 1 chip to a
pipelined slice unchanged.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import compat
from . import mesh as mesh_lib


def _rotate_perm(size: int):
    return [(i, (i + 1) % size) for i in range(size)]


def _pin_replicated(tree, mesh):
    """Commit a replicated layout on shard_map operands computed INSIDE
    an enclosing jit (the training-step path stacks stage params at
    trace time). Without the pin, GSPMD is free to pick any layout for
    the intermediate, and a layout that disagrees with the shard_map
    in_specs enters the manual region UNREDUCED on this jax version —
    measured as every stage's params arriving multiplied by the
    data-axis size (data^S after S stages). Eager callers and jit
    arguments already carry committed layouts; the pin is a no-op for
    them.

    Replicated, NOT ``P(pipe)``: the memory-preserving stage-sharded pin
    was tried and hits the same unreduced-entry bug (a P(pipe)-committed
    in-jit stack still arrived ×data-size per stage on jax 0.4.37, see
    ``tests/test_pipeline_parallel.py``'s in-jit regression test's
    history), so per-rank stage-param memory scaling from inside a jit
    waits on the upstream fix. The training-loop path replicates these
    params anyway (no layer declares a pipe param spec), so today this
    costs nothing it wasn't already paying.

    zoolint's ZL026 caller prong enforces this bug class: a trace-time
    stacked tree passed into a shard_map site must route through a
    ``with_sharding_constraint`` pin (this helper qualifies), so new
    step builders that skip the pin fail lint instead of training
    ×data-size."""
    repl = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(a, repl), tree)


def gpipe_apply(stage_fn: Callable, stacked_params, x, *, mesh,
                n_micro: int, rng=None, stages_per_rank: int = 1):
    """Run ``x`` through ``S`` stacked stages with the GPipe schedule.

    ``stage_fn(params, x, rng) -> y`` is one stage; ``stacked_params`` has
    leading dim ``total_stages`` on every leaf, sharded over ``pipe``; ``x``
    is the global batch ``(B, ...)`` (sharded over ``data``). With
    ``stages_per_rank`` k > 1 each pipe rank owns k consecutive stages and
    applies them back-to-back per tick (a deeper pipeline than chips). The
    per-data-shard batch must divide by ``n_micro``; wall-clock per batch
    is ``(n_micro + P - 1)`` superstage times (P = pipe size), the classic
    GPipe bubble — raise ``n_micro`` to amortize it.
    """
    S = mesh.shape[mesh_lib.PIPE_AXIS]
    dp = mesh.shape[mesh_lib.DATA_AXIS]
    B = x.shape[0]
    if B % dp != 0:
        raise ValueError(
            f"batch {B} not divisible by the data axis size {dp}")
    if (B // dp) % n_micro != 0:
        raise ValueError(
            f"per-shard batch {B // dp} not divisible by n_micro={n_micro}")

    # one PartitionSpec prefix per argument: params split stage-wise over
    # pipe, batch split over data (replicated over pipe)
    pspec = jax.tree.map(lambda _: P(mesh_lib.PIPE_AXIS), stacked_params)

    @functools.partial(
        compat.shard_map, mesh=mesh,
        in_specs=(pspec, P(mesh_lib.DATA_AXIS)),
        out_specs=P(mesh_lib.DATA_AXIS),
        check_vma=False)
    def run(params_loc, x_loc):
        r = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
        mbs = x_loc.reshape(n_micro, x_loc.shape[0] // n_micro,
                            *x_loc.shape[1:])

        def super_stage(h, t):
            """The rank's k consecutive stages applied back-to-back."""
            def body(h, sp):
                p_j, j = sp
                # unique key per (tick, rank, local stage) = per
                # (microbatch, stage): stochastic stages decorrelate across
                # the schedule (exact rng-stream parity with the sequential
                # path is impossible — it draws once per stage for the
                # whole batch)
                srng = (jax.random.fold_in(jax.random.fold_in(
                    jax.random.fold_in(rng, t), r), j)
                    if rng is not None else None)
                return stage_fn(p_j, h, srng), None

            h, _ = jax.lax.scan(
                body, h, (params_loc, jnp.arange(stages_per_rank)))
            return h

        def tick(carry, t):
            state, out = carry
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(r == 0, feed, state)
            y = super_stage(inp, t)
            # the last rank retires microbatch t-(S-1) at tick t
            widx = jnp.clip(t - (S - 1), 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            keep = jnp.logical_and(r == S - 1, t >= S - 1)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(keep, y, cur), widx, 0)
            state = jax.lax.ppermute(y, mesh_lib.PIPE_AXIS, _rotate_perm(S))
            return (state, out), None

        out0 = jnp.zeros_like(mbs)
        (_, out), _ = jax.lax.scan(tick, (jnp.zeros_like(mbs[0]), out0),
                                   jnp.arange(n_micro + S - 1))
        # results live on the last rank only; masked psum broadcasts them so
        # every pipe rank returns the same (replicated) value
        out = jax.lax.psum(jnp.where(r == S - 1, out, jnp.zeros_like(out)),
                           mesh_lib.PIPE_AXIS)
        return out.reshape(x_loc.shape)

    return run(_pin_replicated(stacked_params, mesh), x)


def hetero_gpipe_apply(stage_fns, stacked_vec, x_wire, *, mesh,
                       n_micro: int, rng=None):
    """GPipe schedule over HETEROGENEOUS stages (VERDICT r4 missing #2:
    ``embedding → blocks → head`` as ONE pipelined model, arbitrary layer
    cuts, per-stage distinct param trees and activation shapes).

    SPMD can't run different programs per rank, so heterogeneity is encoded
    data-side: every stage's params are raveled into one row of the
    ``(S, L)`` ``stacked_vec`` (padded to the longest stage; sharded over
    ``pipe`` so each rank holds ONLY its stage's weights), activations
    travel in a common ``(B_micro, W)`` float32 wire format (padded to the
    widest stage boundary; f32 carries bf16 activations and int token ids
    exactly — ids are < 2^24), and each tick every rank runs
    ``lax.switch(rank, stage_fns)`` — all S branches are compiled
    everywhere, each rank executes exactly one, the XLA-native equivalent
    of per-stage programs.

    ``stage_fns[j](vec_row, h_wire, rng) -> h_wire`` unpacks its own slice
    layout statically. Schedule, bubble, and autodiff story are identical
    to ``gpipe_apply``.
    """
    S = mesh.shape[mesh_lib.PIPE_AXIS]
    dp = mesh.shape[mesh_lib.DATA_AXIS]
    B, W = x_wire.shape
    if B % dp != 0:
        raise ValueError(f"batch {B} not divisible by data axis size {dp}")
    if (B // dp) % n_micro != 0:
        raise ValueError(
            f"per-shard batch {B // dp} not divisible by n_micro={n_micro}")

    @functools.partial(
        compat.shard_map, mesh=mesh,
        in_specs=(P(mesh_lib.PIPE_AXIS), P(mesh_lib.DATA_AXIS)),
        out_specs=P(mesh_lib.DATA_AXIS),
        check_vma=False)
    def run(vec_loc, x_loc):
        r = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
        vec = vec_loc[0]                                    # (L,)
        mbs = x_loc.reshape(n_micro, x_loc.shape[0] // n_micro, W)

        def tick(carry, t):
            state, out = carry
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(r == 0, feed, state)
            trng = jax.random.fold_in(rng, t) if rng is not None else None
            y = jax.lax.switch(
                r, [functools.partial(fn, rng=trng) for fn in stage_fns],
                vec, inp)
            widx = jnp.clip(t - (S - 1), 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            keep = jnp.logical_and(r == S - 1, t >= S - 1)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(keep, y, cur), widx, 0)
            state = jax.lax.ppermute(y, mesh_lib.PIPE_AXIS, _rotate_perm(S))
            return (state, out), None

        out0 = jnp.zeros_like(mbs)
        (_, out), _ = jax.lax.scan(tick, (jnp.zeros_like(mbs[0]), out0),
                                   jnp.arange(n_micro + S - 1))
        out = jax.lax.psum(jnp.where(r == S - 1, out, jnp.zeros_like(out)),
                           mesh_lib.PIPE_AXIS)
        return out.reshape(x_loc.shape)

    return run(_pin_replicated(stacked_vec, mesh), x_wire)


def sequential_apply(stage_fn: Callable, stacked_params, x, n_stages: int,
                     rng=None):
    """Portability fallback (pipe axis == 1): the same stacked tree runs as
    a sequential ``lax.scan`` over stages — identical math for deterministic
    stages, one device. ``n_stages`` comes from the caller: the param tree
    may be empty (parameter-less stages like Dropout)."""
    def body(h, sp):
        p_stage, i = sp
        trng = jax.random.fold_in(rng, i) if rng is not None else None
        return stage_fn(p_stage, h, trng), None

    y, _ = jax.lax.scan(body, x, (stacked_params, jnp.arange(n_stages)))
    return y
