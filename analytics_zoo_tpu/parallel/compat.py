"""jax API compatibility shims for the parallel layer.

The ONE place version drift between jax releases is absorbed
(ROADMAP standing constraint): ``shard_map`` graduated from
``jax.experimental.shard_map.shard_map`` to ``jax.shard_map``, and the
replication-check keyword was renamed ``check_rep`` → ``check_vma``
along the way. Callers in this package write the NEW spelling
(``jax.shard_map``-style kwargs, ``check_vma=``); this module resolves
whichever implementation the installed jax actually ships and maps the
keyword accordingly, so ``parallel/ring_attention.py`` and
``parallel/pipeline.py`` never need per-version branches of their own.
"""

from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map"]


def _resolve():
    """The installed jax's shard_map plus the name its signature uses
    for the replication check (``check_vma`` on current jax,
    ``check_rep`` on the older ``jax.experimental`` form; None when the
    signature is not introspectable — kwarg passed through untouched)."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return fn, None
    for name in ("check_vma", "check_rep"):
        if name in params:
            return fn, name
    return fn, None


_SHARD_MAP, _CHECK_KW = _resolve()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """Feature-detected ``jax.shard_map``. Accepts the current-jax
    keyword spelling (``check_vma=``) and forwards it under whatever
    name the installed implementation expects; extra kwargs pass
    through untouched."""
    if check_vma is not None:
        if _CHECK_KW is not None:
            kwargs[_CHECK_KW] = check_vma
        else:
            kwargs["check_vma"] = check_vma
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
