"""Minimal RESP (REdis Serialization Protocol) client — the wire layer under
``RedisBackend`` when the ``redis`` package isn't installed.

The reference hard-requires a Redis server plus the redis-py client
(``pyzoo/zoo/serving/client.py:58-142``); here the backend speaks the actual
wire protocol itself over one TCP socket, covering exactly the command
subset the serving contract uses: XADD / XLEN / XREAD / XDEL (input
stream), HSET / HGETALL / DEL / KEYS (``result:<uri>`` hashes), PING.
RESP2 framing: arrays of bulk strings out, simple/bulk/integer/array
replies in. Connections come from a small shared pool (created on demand,
bounded by peak concurrency, like redis-py's): the serving loop's blocking
XREAD never holds up a producer thread's ``xadd``/``set_result``, and a
connection that errors mid-command (timeout, partial read) is DISCARDED,
never returned to the pool — a desynced socket would answer the next
command with the previous command's late reply.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["RespClient", "RespError", "RespPipeline"]


class RespError(RuntimeError):
    """Server returned an error reply (``-ERR ...``)."""


def _frame(parts) -> bytes:
    """One RESP command frame: an array of bulk strings. Values may be
    str (utf-8 encoded), int/float (decimal text), or bytes (sent raw —
    RESP bulk strings are length-prefixed, so binary payloads like the
    v2 tensor bytes pass untouched)."""
    out = [b"*%d\r\n" % len(parts)]
    for p in parts:
        if isinstance(p, str):
            p = p.encode()
        elif isinstance(p, (int, float)):
            p = str(p).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(p), p))
    return b"".join(out)


class _Conn:
    """One socket + read buffer (single-thread use)."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""

    def send(self, *parts) -> None:
        self.sock.sendall(_frame(parts))

    def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]  # strip \r\n
        return data

    def read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n == -1 else [self.read_reply()
                                         for _ in range(n)]
        raise RespError(f"unparseable reply start {line!r}")

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class RespClient:
    def __init__(self, host: str = "localhost", port: int = 6379,
                 timeout: float = 30.0):
        self._host, self._port, self._timeout = host, port, timeout
        self._pool: List[_Conn] = []
        self._pool_lock = threading.Lock()
        self._closed = False
        self._release(_Conn(host, port, timeout))  # eager: bad host fails now

    def _acquire(self) -> _Conn:
        if self._closed:
            raise RuntimeError("RespClient is closed")
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return _Conn(self._host, self._port, self._timeout)

    def _release(self, c: _Conn) -> None:
        with self._pool_lock:
            if self._closed:
                c.close()
            else:
                self._pool.append(c)

    def close(self):
        with self._pool_lock:
            self._closed = True
            for c in self._pool:
                c.close()
            self._pool.clear()

    def command(self, *parts):
        c = self._acquire()
        try:
            c.send(*parts)
            reply = c.read_reply()
        except RespError:
            # protocol-level error reply: the stream stayed in sync
            self._release(c)
            raise
        except Exception:
            # timeout / partial read / connection loss: the socket may hold
            # a late reply that would answer the NEXT command — discard it
            c.close()
            raise
        self._release(c)
        return reply

    def execute_many(self, commands):
        """Pipelined execution: write every command frame in ONE socket
        send, then read the replies back in order — one network round
        trip for the whole batch (how the async publisher lands a
        batch's result hashes). An error REPLY keeps the stream in sync
        (remaining replies are still read, the first error raises after
        the pass); a transport error discards the connection like
        :meth:`command` does."""
        commands = list(commands)
        if not commands:
            return []
        c = self._acquire()
        replies, first_err = [], None
        try:
            c.sock.sendall(b"".join(_frame(parts) for parts in commands))
            for _ in commands:
                try:
                    replies.append(c.read_reply())
                except RespError as e:
                    replies.append(e)
                    if first_err is None:
                        first_err = e
        except Exception:
            # timeout / partial read / connection loss mid-batch: the
            # socket may hold late replies that would answer the NEXT
            # command — discard it, never return it to the pool
            c.close()
            raise
        self._release(c)
        if first_err is not None:
            raise first_err
        return replies

    def pipeline(self) -> "RespPipeline":
        """A command buffer matching the slice of redis-py's pipeline
        surface ``RedisBackend`` uses (``hset`` + ``execute``)."""
        return RespPipeline(self)

    # -- the redis-py surface RedisBackend uses ------------------------------
    def ping(self) -> bool:
        return self.command("PING") in (b"PONG", "PONG")

    def xadd(self, stream: str, fields: Dict) -> bytes:
        args: List = ["XADD", stream, "*"]
        for k, v in fields.items():
            args += [k, v]
        return self.command(*args)

    def xlen(self, stream: str) -> int:
        return int(self.command("XLEN", stream))

    def xread(self, streams: Dict[str, str], count: Optional[int] = None,
              block: Optional[int] = None):
        args: List = ["XREAD"]
        if count is not None:
            args += ["COUNT", count]
        if block is not None:
            args += ["BLOCK", block]
        args += ["STREAMS"] + list(streams.keys()) + list(streams.values())
        resp = self.command(*args)
        if resp is None:
            return []
        out = []
        for name, entries in resp:
            decoded = []
            for eid, kv in entries:
                fields = {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)}
                decoded.append((eid, fields))
            out.append((name, decoded))
        return out

    def xdel(self, stream: str, entry_id: str) -> int:
        return int(self.command("XDEL", stream, entry_id))

    def hset(self, key: str, mapping: Dict) -> int:
        args: List = ["HSET", key]
        for k, v in mapping.items():
            args += [k, v]
        return int(self.command(*args))

    def hgetall(self, key: str) -> Dict[bytes, bytes]:
        resp = self.command("HGETALL", key) or []
        return {resp[i]: resp[i + 1] for i in range(0, len(resp), 2)}

    def delete(self, key: str) -> int:
        return int(self.command("DEL", key))

    def keys(self, pattern: str) -> List[bytes]:
        return self.command("KEYS", pattern) or []


class RespPipeline:
    """Buffered commands flushed through :meth:`RespClient.execute_many`
    in one round trip. Only the commands ``RedisBackend.set_results``
    queues are implemented; extend as the backend grows."""

    def __init__(self, client: RespClient):
        self._client = client
        self._commands: List[tuple] = []

    def hset(self, key: str, mapping: Dict) -> "RespPipeline":
        args: List = ["HSET", key]
        for k, v in mapping.items():
            args += [k, v]
        self._commands.append(tuple(args))
        return self

    def execute(self) -> List:
        commands, self._commands = self._commands, []
        return self._client.execute_many(commands)
