"""Minimal RESP (REdis Serialization Protocol) client — the wire layer under
``RedisBackend`` when the ``redis`` package isn't installed.

The reference hard-requires a Redis server plus the redis-py client
(``pyzoo/zoo/serving/client.py:58-142``); here the backend speaks the actual
wire protocol itself over one TCP socket, covering exactly the command
subset the serving contract uses: XADD / XLEN / XREAD / XDEL (input
stream), XGROUP / XREADGROUP / XACK / XPENDING / XCLAIM (consumer-group
fleet serving), HSET / HGETALL / HDEL / DEL / KEYS (``result:<uri>``
hashes + the fleet heartbeat hash), PING.
RESP2 framing: arrays of bulk strings out, simple/bulk/integer/array
replies in. Connections come from a small shared pool (created on demand,
bounded by peak concurrency, like redis-py's): the serving loop's blocking
XREAD never holds up a producer thread's ``xadd``/``set_result``, and a
connection that errors mid-command (timeout, partial read) is DISCARDED,
never returned to the pool — a desynced socket would answer the next
command with the previous command's late reply.

Transparent reconnect (``docs/guides/RELIABILITY.md``): a transport
error (``ConnectionError``/``OSError``) discards the socket and — for
**idempotent** commands — retries on a fresh connection under the
client's ``RetryPolicy`` (backoff + bounded attempts), counting each
round in ``zoo_backend_reconnects_total{backend="resp"}``. The
classification is per-op: every command in the serving contract is
idempotent-in-effect (re-running XLEN/XREAD/HGETALL/KEYS/PING reads the
same state; HSET re-writes the same fields; DEL/XDEL/HDEL/XACK of a gone
key is a no-op; a retried XCLAIM finds nothing left idle) EXCEPT
``XADD``, whose server-assigned entry id means a blind retry could
enqueue — and serve, and bill — the same record twice, and
``XREADGROUP``, whose delivery side effect (entries landing in the PEL)
a lost reply would orphan twice over. Both stay at-most-once: the error
propagates to the caller — the producer owns the re-enqueue decision,
and the consumer's own reclaim sweep recovers a lost delivery. Pipelines retry as a unit only when
every buffered command is idempotent; a retry discards all partial
replies from the dead socket (they can never pair with the new
connection's stream).
"""

from __future__ import annotations

import itertools
import logging
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import faults
from ..common.reliability import RetryPolicy

log = logging.getLogger("analytics_zoo_tpu.serving.resp")

__all__ = ["RespClient", "RespError", "RespPipeline"]

#: commands whose blind re-execution changes observable state — everything
#: else in the serving contract may retry transparently (see module doc).
#: XREADGROUP joins XADD: with ``>`` it DELIVERS new entries into the
#: group's PEL — a reply lost in transit leaves them owned by this
#: consumer, and a blind retry would pull a fresh set on top. The
#: originals are not lost (the consumer's own reclaim sweep re-claims
#: them once idle), so one attempt + propagate is the safe contract.
#: XCLAIM stays retryable: an applied-then-dropped claim just means the
#: retry finds nothing idle — the entries sit in OUR pel until the next
#: sweep; nothing is double-applied.
_NON_IDEMPOTENT = frozenset({"XADD", "XREADGROUP"})


class RespError(RuntimeError):
    """Server returned an error reply (``-ERR ...``)."""


def _frame(parts) -> bytes:
    """One RESP command frame: an array of bulk strings. Values may be
    str (utf-8 encoded), int/float (decimal text), or bytes (sent raw —
    RESP bulk strings are length-prefixed, so binary payloads like the
    v2 tensor bytes pass untouched)."""
    out = [b"*%d\r\n" % len(parts)]
    for p in parts:
        if isinstance(p, str):
            p = p.encode()
        elif isinstance(p, (int, float)):
            p = str(p).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(p), p))
    return b"".join(out)


class _Conn:
    """One socket + read buffer (single-thread use)."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""

    def send(self, *parts) -> None:
        self.sock.sendall(_frame(parts))

    def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]  # strip \r\n
        return data

    def read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n == -1 else [self.read_reply()
                                         for _ in range(n)]
        raise RespError(f"unparseable reply start {line!r}")

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class RespClient:
    def __init__(self, host: str = "localhost", port: int = 6379,
                 timeout: float = 30.0, retry: Optional[RetryPolicy] = None,
                 registry=None):
        self._host, self._port, self._timeout = host, port, timeout
        #: reconnect/retry schedule for idempotent commands; pass a seeded
        #: policy for deterministic backoff in tests
        self._retry = retry if retry is not None else RetryPolicy(
            max_attempts=4, base_delay=0.05, max_delay=1.0)
        self._pool: List[_Conn] = []
        self._pool_lock = threading.Lock()
        self._closed = False
        self._m_reconnects = None
        if registry is None:
            from ..observability import default_registry
            registry = default_registry()
        self._m_reconnects = registry.counter(
            "zoo_backend_reconnects_total",
            "transport errors answered with a reconnect + retry",
            labels={"backend": "resp"})
        self._release(_Conn(host, port, timeout))  # eager: bad host fails now

    def _acquire(self) -> _Conn:
        if self._closed:
            raise RuntimeError("RespClient is closed")
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return _Conn(self._host, self._port, self._timeout)

    def _release(self, c: _Conn) -> None:
        with self._pool_lock:
            if self._closed:
                c.close()
            else:
                self._pool.append(c)

    def close(self):
        with self._pool_lock:
            self._closed = True
            for c in self._pool:
                c.close()
            self._pool.clear()

    @staticmethod
    def _op_name(parts) -> str:
        op = parts[0] if parts else ""
        if isinstance(op, bytes):
            op = op.decode("ascii", "replace")
        return str(op).upper()

    def _retries(self, retryable: bool):
        """The reconnect schedule for one logical command: a leading None
        (the first attempt sleeps nothing), then the policy's backoff
        delays — empty for non-idempotent ops, which get ONE attempt."""
        if not retryable:
            return iter((None,))
        return itertools.chain((None,), self._retry.delays())

    def _run_with_reconnect(self, label: str, retryable: bool, attempt):
        """The one reconnect-retry scaffold both surfaces share:
        acquire a connection, run ``attempt(conn)``, release on success.
        A transport error (connect refused / timeout / partial read /
        loss) DISCARDS the socket — it may hold a late reply that would
        answer the next command — and, for retryable work only, retries
        fresh under the backoff schedule, counting each round in the
        reconnect metric. ``attempt`` raising :class:`RespError` means
        the reply stream stayed in sync: the connection is released and
        the error propagates without a retry. Any other exception
        discards the connection and propagates."""
        last: Optional[BaseException] = None
        for delay in self._retries(retryable):
            if delay is not None:
                self._m_reconnects.inc()
                log.warning("resp %s hit %s; reconnecting in %.3fs",
                            label, last, delay)
                if delay > 0:
                    time.sleep(delay)
            c: Optional[_Conn] = None
            try:
                c = self._acquire()     # may itself fail: server down
                result = attempt(c)
            except RespError:
                self._release(c)
                raise
            except (ConnectionError, OSError) as e:
                if c is not None:
                    c.close()
                last = e
                continue
            except Exception:
                if c is not None:
                    c.close()
                raise
            self._release(c)
            return result
        assert last is not None
        raise last

    def command(self, *parts):
        op = self._op_name(parts)

        def attempt(c: _Conn):
            # chaos sites (docs/guides/RELIABILITY.md): one fire per
            # logical command attempt, BEFORE the socket op it models —
            # a `disconnect` here exercises the exact reconnect/idempotency
            # rules a dropped TCP connection would, against a REAL backend
            faults.inject("resp.send")
            c.send(*parts)
            faults.inject("resp.recv")
            return c.read_reply()

        return self._run_with_reconnect(op, op not in _NON_IDEMPOTENT,
                                        attempt)

    def execute_many(self, commands):
        """Pipelined execution: write every command frame in ONE socket
        send, then read the replies back in order — one network round
        trip for the whole batch (how the async publisher lands a
        batch's result hashes). An error REPLY keeps the stream in sync
        (remaining replies are still read, the first error raises after
        the pass). A transport error discards the connection and — when
        every command in the batch is idempotent — retries the WHOLE
        batch on a fresh one, dropping any partial replies read off the
        dead socket (a reply that might pair with an un-applied command
        must never be surfaced). A batch containing a non-idempotent
        command (XADD) never retries: the error propagates with the
        stream state at most once-applied."""
        commands = list(commands)
        if not commands:
            return []
        retryable = all(self._op_name(c) not in _NON_IDEMPOTENT
                        for c in commands)

        def attempt(c: _Conn):
            faults.inject("resp.send")   # once per pipeline attempt
            c.sock.sendall(b"".join(_frame(parts) for parts in commands))
            faults.inject("resp.recv")
            replies, first_err = [], None
            for _ in commands:
                try:
                    replies.append(c.read_reply())
                except RespError as e:
                    # an error REPLY: the stream stays in sync — keep
                    # reading so later replies pair with their commands
                    replies.append(e)
                    if first_err is None:
                        first_err = e
            return replies, first_err

        replies, first_err = self._run_with_reconnect(
            f"pipeline({len(commands)} cmds)", retryable, attempt)
        if first_err is not None:
            raise first_err
        return replies

    def pipeline(self) -> "RespPipeline":
        """A command buffer matching the slice of redis-py's pipeline
        surface ``RedisBackend`` uses (``hset`` + ``execute``)."""
        return RespPipeline(self)

    # -- the redis-py surface RedisBackend uses ------------------------------
    def ping(self) -> bool:
        return self.command("PING") in (b"PONG", "PONG")

    def xadd(self, stream: str, fields: Dict) -> bytes:
        args: List = ["XADD", stream, "*"]
        for k, v in fields.items():
            args += [k, v]
        return self.command(*args)

    def xlen(self, stream: str) -> int:
        return int(self.command("XLEN", stream))

    def xread(self, streams: Dict[str, str], count: Optional[int] = None,
              block: Optional[int] = None):
        args: List = ["XREAD"]
        if count is not None:
            args += ["COUNT", count]
        if block is not None:
            args += ["BLOCK", block]
        args += ["STREAMS"] + list(streams.keys()) + list(streams.values())
        resp = self.command(*args)
        if resp is None:
            return []
        out = []
        for name, entries in resp:
            decoded = []
            for eid, kv in entries:
                fields = {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)}
                decoded.append((eid, fields))
            out.append((name, decoded))
        return out

    def xdel(self, stream: str, *entry_ids: str) -> int:
        return int(self.command("XDEL", stream, *entry_ids))

    # -- consumer groups (docs/guides/SERVING.md) ----------------------------
    def xgroup_create(self, stream: str, group: str) -> None:
        """XGROUP CREATE from id 0 with MKSTREAM; raises RespError
        (BUSYGROUP) when the group exists — ``RedisBackend`` swallows
        that one, making creation idempotent at its layer."""
        self.command("XGROUP", "CREATE", stream, group, "0", "MKSTREAM")

    def xreadgroup(self, group: str, consumer: str,
                   streams: Dict[str, str], count: Optional[int] = None,
                   block: Optional[int] = None):
        """Same reply shape as :meth:`xread`, read through a group
        (non-idempotent: one attempt, see ``_NON_IDEMPOTENT``)."""
        args: List = ["XREADGROUP", "GROUP", group, consumer]
        if count is not None:
            args += ["COUNT", count]
        if block is not None:
            args += ["BLOCK", block]
        args += ["STREAMS"] + list(streams.keys()) + list(streams.values())
        resp = self.command(*args)
        if resp is None:
            return []
        out = []
        for name, entries in resp:
            decoded = []
            for eid, kv in entries or []:
                if kv is None:      # a deleted entry still in the PEL
                    continue
                fields = {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)}
                decoded.append((eid, fields))
            out.append((name, decoded))
        return out

    def xack(self, stream: str, group: str, *entry_ids: str) -> int:
        return int(self.command("XACK", stream, group, *entry_ids))

    def xpending_range(self, stream: str, group: str, min_idle_ms: int,
                       count: int) -> List[tuple]:
        """Extended XPENDING, idle-filtered: ``[(id, consumer,
        delivery_count), ...]`` for up to ``count`` entries idle at
        least ``min_idle_ms`` — the reclaim sweep's candidate list."""
        resp = self.command("XPENDING", stream, group, "IDLE",
                            int(min_idle_ms), "-", "+", count) or []
        return [(eid, consumer, int(times))
                for eid, consumer, _idle, times in resp]

    def xpending_summary(self, stream: str, group: str) -> Dict[str, int]:
        """Summary XPENDING: per-consumer pending counts."""
        resp = self.command("XPENDING", stream, group)
        if not resp or resp[3] is None:
            return {}
        out: Dict[str, int] = {}
        for consumer, n in resp[3]:
            key = consumer.decode() if isinstance(consumer, bytes) \
                else str(consumer)
            out[key] = int(n)
        return out

    def xclaim(self, stream: str, group: str, consumer: str,
               min_idle_ms: int, entry_ids: List[str]):
        """``[(id, fields_or_None), ...]`` for the entries actually
        transferred; ids whose idle clock was reset by a racing claimer
        are simply absent from the reply."""
        resp = self.command("XCLAIM", stream, group, consumer,
                            int(min_idle_ms), *entry_ids) or []
        out = []
        for item in resp:
            if item is None:
                continue
            eid, kv = item
            fields = None if kv is None else \
                {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)}
            out.append((eid, fields))
        return out

    def hset(self, key: str, mapping: Dict) -> int:
        args: List = ["HSET", key]
        for k, v in mapping.items():
            args += [k, v]
        return int(self.command(*args))

    def hgetall(self, key: str) -> Dict[bytes, bytes]:
        resp = self.command("HGETALL", key) or []
        return {resp[i]: resp[i + 1] for i in range(0, len(resp), 2)}

    def hdel(self, key: str, *fields: str) -> int:
        return int(self.command("HDEL", key, *fields))

    def delete(self, key: str) -> int:
        return int(self.command("DEL", key))

    def keys(self, pattern: str) -> List[bytes]:
        return self.command("KEYS", pattern) or []


class RespPipeline:
    """Buffered commands flushed through :meth:`RespClient.execute_many`
    in one round trip. Only the commands ``RedisBackend.set_results``
    queues are implemented; extend as the backend grows."""

    def __init__(self, client: RespClient):
        self._client = client
        self._commands: List[tuple] = []

    def hset(self, key: str, mapping: Dict) -> "RespPipeline":
        args: List = ["HSET", key]
        for k, v in mapping.items():
            args += [k, v]
        self._commands.append(tuple(args))
        return self

    def execute(self) -> List:
        commands, self._commands = self._commands, []
        return self._client.execute_many(commands)
