"""Cluster Serving — the serving loop, parity with
``serving/ClusterServing.scala:103-134,243-289`` re-designed for a TPU chip:

* the reference runs a Spark-streaming micro-batch per trigger; here one
  background thread drains the input stream and pushes through a jitted
  ``InferenceModel`` (replica-queue concurrency inside),
* requests are batched up to ``batch_size`` per dispatch — padding to a
  fixed shape inside ``InferenceModel.predict`` keeps ONE compiled program
  regardless of how many requests arrived (dynamic batch sizes would
  recompile per unique size),
* backpressure comes from the bounded stream (``LocalBackend.xadd`` blocks),
  replacing the reference's Redis-memory watermark polling.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import numpy as np

from .backend import LocalBackend, default_backend
from .client import INPUT_STREAM, decode_array, encode_array

log = logging.getLogger("analytics_zoo_tpu.serving")

__all__ = ["ClusterServing"]


class ClusterServing:
    """Owns the serve loop: xread → batched predict → result writes."""

    def __init__(self, model, backend: Optional[LocalBackend] = None,
                 batch_size: int = 32, stream: str = INPUT_STREAM,
                 block_ms: int = 50):
        self.model = model          # InferenceModel (or any .predict(x))
        self.backend = backend if backend is not None else default_backend()
        self.batch_size = int(batch_size)
        self.stream = stream
        self.block_ms = int(block_ms)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.served = 0             # records processed (visible for tests/ops)
        self._summary = None        # InferenceSummary role (TB scalars)
        self._batches = 0
        self._t_last_flush = None   # throughput-interval anchor

    def set_tensorboard(self, log_dir: str,
                        app_name: str = "serving") -> "ClusterServing":
        """Write per-batch "Serving Throughput" / "Serving Records" scalars
        (the reference's throughput-to-TensorBoard path,
        ``ClusterServing.scala:291-317`` + ``InferenceSummary.scala``).
        Call before ``start()``."""
        import os
        from ..utils.tensorboard import EventFileWriter
        if self._summary is not None:  # redirecting: release the old fd
            self._summary.close()
        self._summary = EventFileWriter(os.path.join(log_dir, app_name))
        return self

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ClusterServing":
        if self._thread is not None:
            raise RuntimeError("serving already started")
        self._stop.clear()
        self._t_last_flush = None   # a restart must not span the downtime
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cluster-serving")
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the loop; with ``drain`` first wait for the stream to empty."""
        if self._thread is None:
            return
        if drain:
            import time
            deadline = time.monotonic() + timeout
            while (self.backend.stream_len(self.stream) > 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        self._stop.set()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # keep the handle: a discarded live thread would let a second
            # start() race two consumers on the same stream
            raise TimeoutError(
                f"serve loop still running after {timeout}s (model dispatch "
                f"in flight?); call stop() again to re-join")
        self._thread = None
        if self._summary is not None:
            self._summary.close()
            self._summary = None

    # -- the loop -----------------------------------------------------------
    def _loop(self) -> None:
        """Two-deep software pipeline: batch N's device time + dispatch
        round-trip runs while batch N+1 is read and decoded on the host
        (``predict_async`` enqueues the XLA work and defers only the
        readback). On a tunneled/remote device the round-trip dominates
        the batch budget, so overlapping it with host work roughly
        doubles sustainable throughput; one batch in flight + one being
        assembled keeps the memory bound."""
        pending = None   # (uris, collect) — dispatched, readback deferred
        try:
            while not self._stop.is_set():
                entries = self.backend.xread(self.stream, self.batch_size,
                                             block_ms=self.block_ms)
                if not entries:
                    if pending is not None:
                        pending = self._flush(pending)
                    continue
                uris, tensors = [], []
                for _, fields in entries:
                    try:
                        # uri first: a decodable payload with a missing
                        # uri must not leave an orphan tensor that would
                        # misalign every later uri with the wrong
                        # prediction
                        uri = fields["uri"]
                        arr = decode_array(fields["data"])
                    except Exception:
                        # write an addressable error so the producer's
                        # query() fails fast instead of blocking out its
                        # full timeout
                        log.exception("undecodable record (uri=%r)",
                                      fields.get("uri"))
                        if fields.get("uri"):
                            self.backend.set_result(
                                fields["uri"],
                                {"error": "undecodable payload"})
                        continue
                    uris.append(uri)
                    tensors.append(arr)
                if not uris:
                    # every record in this read was undecodable: the same
                    # drain signal applies — an empty stream means no next
                    # batch will arrive to trigger the pending readback,
                    # so it would otherwise park for up to block_ms
                    if pending is not None and \
                            self.backend.stream_len(self.stream) == 0:
                        pending = self._flush(pending)
                    continue
                try:
                    batch = np.stack(tensors)
                except ValueError:
                    # ragged shapes can't batch: drain the pipeline, then
                    # serve one by one (rare path, keep it simple)
                    if pending is not None:
                        pending = self._flush(pending)
                    for uri, t in zip(uris, tensors):
                        nxt, _ = self._dispatch([uri], t[None])
                        if nxt is not None:
                            self._flush(nxt)
                    continue
                nxt, pending = self._dispatch(uris, batch, pending)
                if pending is not None:
                    pending = self._flush(pending)
                if nxt is not None and \
                        self.backend.stream_len(self.stream) == 0:
                    # nothing left queued: the stream is drained and there
                    # is no next batch to overlap with, so deferring this
                    # readback would only add up to block_ms of tail
                    # latency under trickle load (ADVICE round 5). The
                    # queue length is the drain signal — an under-full
                    # read is not (xread returns on FIRST delivery, so
                    # under sustained single-record load more work is
                    # usually queued already and flushing would serialize
                    # the two-deep pipeline), and a final exactly-full
                    # batch with an empty queue must flush too
                    nxt = self._flush(nxt)
                pending = nxt
        finally:
            if pending is not None:
                self._flush(pending)

    def _dispatch(self, uris, batch, pending=None):
        """Enqueue the device work; ((uris, collect, t0), leftover_pending).
        Tries a NON-blocking async dispatch first: with a single replica
        permit (``concurrent_num=1``) dispatching before collecting our
        own pending batch would deadlock, so on a busy model the pending
        batch is flushed (releasing its permit) and the dispatch retried
        blocking. Models without predict_async (the server accepts any
        ``.predict``) compute synchronously — there is nothing to overlap,
        so the pending batch is flushed BEFORE the blocking predict and
        this batch publishes immediately (deferring either one would only
        add latency). Returns (None, pending) when the dispatch failed."""
        import time
        t0 = time.perf_counter()
        try:
            async_fn = getattr(self.model, "predict_async", None)
            if async_fn is not None:
                collect = async_fn(batch, block=False)
                if collect is None:      # all replica permits in flight
                    if pending is not None:
                        pending = self._flush(pending)
                    collect = async_fn(batch)
                return (uris, collect, t0), pending
            if pending is not None:
                pending = self._flush(pending)
            preds = self.model.predict(batch)
            self._flush((uris, (lambda: preds), t0))
            return None, pending
        except Exception:
            log.exception("inference dispatch failed for %d records; "
                          "writing errors", len(uris))
            for uri in uris:
                self.backend.set_result(uri, {"error": "inference failed"})
            return None, pending

    def _flush(self, pending) -> None:
        """Block on a dispatched batch's readback and publish its results.
        Returns None so callers can overwrite their pending slot."""
        import time
        uris, collect, t0 = pending
        try:
            preds = np.asarray(collect())
        except Exception:
            log.exception("inference failed for %d records; writing errors",
                          len(uris))
            for uri in uris:
                self.backend.set_result(uri, {"error": "inference failed"})
            return None
        for i, uri in enumerate(uris):
            self.backend.set_result(uri, {"value": encode_array(preds[i])})
        self.served += len(uris)
        self._batches += 1
        if self._summary is not None:
            now = time.perf_counter()
            t_prev = self._t_last_flush
            self._t_last_flush = now
            # interval start = the later of (previous flush, this batch's
            # dispatch): under continuous load that is the inter-flush
            # interval (steady-state rate, no double-counting the
            # overlapped round-trip); after an idle gap it is this batch's
            # own dispatch→publish time (idle poll time must not read as
            # a throughput collapse)
            start = t0 if t_prev is None else max(t_prev, t0)
            dt = max(now - start, 1e-9)
            self._summary.add_scalar("Serving Throughput", len(uris) / dt,
                                     self._batches)
            self._summary.add_scalar("Serving Records", self.served,
                                     self._batches)
            self._summary.flush()
        return None
